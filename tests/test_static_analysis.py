"""tools/rtcheck: the invariant-encoding static analysis suite.

Per pass: one minimal bad fixture that MUST produce the finding and its
fixed twin that MUST be clean — the checker's contract is exactly "this
bug class cannot land silently". Plus the tier-1 gate: rtcheck over the
real tree (ray_tpu/ + tools/) is clean against an empty baseline and stays
under the 10s budget (warm runs ride the per-file content-hash cache).
"""

import json
import os
import sys
import textwrap
import time

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools.rtcheck import core  # noqa: E402
from tools.rtcheck.passes.async_blocking import AsyncBlockingPass  # noqa: E402
from tools.rtcheck.passes.exception_taxonomy import ExceptionTaxonomyPass  # noqa: E402
from tools.rtcheck.passes.knob_registry import KnobRegistryPass  # noqa: E402
from tools.rtcheck.passes.lock_discipline import LockDisciplinePass  # noqa: E402
from tools.rtcheck.passes.wire_schema import WireSchemaPass  # noqa: E402


def run_fixture(tmp_path, files: dict, passes, roots=("ray_tpu",)):
    """Materialize a mini-repo and run the given passes over it."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return core.run(roots, root=str(tmp_path), use_cache=False,
                    baseline_path=str(tmp_path / "no_baseline.json"),
                    passes=passes)


def messages(res):
    return [f.render() for f in res.findings]


# ----------------------------------------------------------- async-blocking
BAD_ASYNC = {
    "ray_tpu/_private/svc.py": """
        import time

        async def handler():
            time.sleep(0.1)

        async def reader(path):
            f = open(path)
            return f.read()

        async def locked(self):
            self._lock.acquire()

        def sync_helper():
            time.sleep(0.1)  # sync function: allowed
    """,
}

GOOD_ASYNC = {
    "ray_tpu/_private/svc.py": """
        import asyncio
        import time

        async def handler():
            await asyncio.sleep(0.1)

        async def reader(path):
            def _read():
                with open(path) as f:  # nested sync closure: executor-side
                    return f.read()
            return await asyncio.get_running_loop().run_in_executor(
                None, _read)

        async def locked(self):
            if not self._lock.acquire(timeout=1.0):
                raise TimeoutError
    """,
}


def test_async_blocking_bad(tmp_path):
    res = run_fixture(tmp_path, BAD_ASYNC, [AsyncBlockingPass()])
    msgs = "\n".join(messages(res))
    assert "time.sleep" in msgs
    assert "open()" in msgs
    assert "acquire" in msgs
    # 4: sleep, open, the follow-on f.read() on the opened handle, acquire;
    # sync_helper stays clean.
    assert len(res.findings) == 4, msgs


def test_async_blocking_good(tmp_path):
    res = run_fixture(tmp_path, GOOD_ASYNC, [AsyncBlockingPass()])
    assert res.ok, messages(res)


def test_directive_inside_string_does_not_suppress(tmp_path):
    """Directives count only as real comments: a string literal that
    happens to contain the suppression syntax (help text, docs) must not
    disable the gate for its neighbors."""
    files = {
        "ray_tpu/_private/svc.py": '''
            import time

            async def handler():
                doc = "example: # rtcheck: disable=async-blocking"
                time.sleep(0.1)
                return doc
        ''',
    }
    res = run_fixture(tmp_path, files, [AsyncBlockingPass()])
    assert len(res.findings) == 1, messages(res)


def test_async_blocking_suppression(tmp_path):
    files = {
        "ray_tpu/_private/svc.py": """
            import time

            async def handler():
                # deliberate: sub-ms sleep in a test-only shim
                time.sleep(0.001)  # rtcheck: disable=async-blocking
        """,
    }
    res = run_fixture(tmp_path, files, [AsyncBlockingPass()])
    assert res.ok, messages(res)


# -------------------------------------------------------------- wire-schema
BAD_WIRE = {
    "ray_tpu/_private/proto.py": """
        def encode(x):
            return (x.a, x.b, x.c, x.d)  # rtcheck: wire=test.rec

        def decode(t):
            a, b, c = t  # rtcheck: wire=test.rec
            return a

        class S:
            def __getstate__(self):
                return (self.a, self.b, self.c, self.d, self.e)

            def __setstate__(self, s):
                if len(s) == 3:  # old snapshots — but arity 4 has no branch
                    s = s + (None, None)
                (self.a, self.b, self.c, self.d, self.e) = s
    """,
}

GOOD_WIRE = {
    "ray_tpu/_private/proto.py": """
        def encode(x):
            return (x.a, x.b, x.c, x.d)  # rtcheck: wire=test.rec

        def decode(t, args=()):
            if len(args) == 9:  # unrelated guard: must not register as a
                return None     # back-compat branch (no [3,4,9] gap)
            if len(t) == 3:  # pre-'d' wire records
                t = t + (None,)
            a, b, c, d = t  # rtcheck: wire=test.rec
            return a

        class S:
            def __getstate__(self):
                return (self.a, self.b, self.c, self.d, self.e)

            def __setstate__(self, s):
                if len(s) == 3:
                    s = s + (None,)
                if len(s) == 4:
                    s = s + (None,)
                (self.a, self.b, self.c, self.d, self.e) = s
    """,
}


def test_wire_schema_bad(tmp_path):
    res = run_fixture(tmp_path, BAD_WIRE, [WireSchemaPass()])
    msgs = "\n".join(messages(res))
    assert "decoder unpacks 3" in msgs and "encoder builds 4" in msgs
    assert "back-compat gap" in msgs, msgs


def test_wire_schema_good(tmp_path):
    res = run_fixture(tmp_path, GOOD_WIRE, [WireSchemaPass()])
    assert res.ok, messages(res)


def test_wire_schema_branch_on_new_arity_is_finding_not_crash(tmp_path):
    """A back-compat branch on the CURRENT (or larger) arity — the
    branched-on-the-new-size typo — is a finding, never an IndexError that
    takes down the whole lint run."""
    files = {
        "ray_tpu/_private/proto.py": """
            def encode(x):
                return (x.a, x.b, x.c)  # rtcheck: wire=test.rec

            def decode(t):
                if len(t) == 6:  # typo: branched on a size we never reach
                    t = t + (None,)
                a, b, c = t  # rtcheck: wire=test.rec
                return a
        """,
    }
    res = run_fixture(tmp_path, files, [WireSchemaPass()])
    msgs = "\n".join(messages(res))
    assert "not below the decoder's arity" in msgs, msgs


def test_wire_schema_file_scoped_invocation(tmp_path):
    """Scanning only task_spec.py on the real tree must not report phantom
    marker deletion for wires whose markers live in other files."""
    res = core.run(("ray_tpu/_private/task_spec.py",), root=REPO_ROOT,
                   use_cache=False, passes=[WireSchemaPass()])
    assert res.ok, messages(res)


def test_wire_schema_half_marked(tmp_path):
    # Deleting the consumer's marker (or the consumer) is itself a finding.
    files = {
        "ray_tpu/_private/proto.py": """
            def encode(x):
                return (x.a, x.b)  # rtcheck: wire=test.rec
        """,
    }
    res = run_fixture(tmp_path, files, [WireSchemaPass()])
    assert any("no marked consumer" in m for m in messages(res))


# ------------------------------------------------------------ knob-registry
MINI_RTCONFIG = """
    _REGISTRY = {}

    def _flag(name, typ, default):
        _REGISTRY[name] = (typ, default)

    _flag("foo_knob", int, 1)
"""

BAD_KNOBS = {
    "ray_tpu/_private/rtconfig.py": MINI_RTCONFIG,
    "ray_tpu/util/thing.py": """
        import os

        UNREGISTERED = os.environ.get("RT_BAR_KNOB", "")
        BYPASS = os.environ.get("RT_FOO_KNOB")
    """,
    "README.md": "no knob table here\n",
}

GOOD_KNOBS = {
    "ray_tpu/_private/rtconfig.py": MINI_RTCONFIG,
    "ray_tpu/util/thing.py": """
        from ray_tpu._private.rtconfig import CONFIG

        def foo():
            return CONFIG.foo_knob
    """,
    "README.md": "| `RT_FOO_KNOB` | 1 | the foo knob |\n",
}


def test_knob_registry_bad(tmp_path):
    res = run_fixture(tmp_path, BAD_KNOBS, [KnobRegistryPass()])
    msgs = "\n".join(messages(res))
    assert "RT_BAR_KNOB is not a registered rtconfig flag" in msgs
    assert "direct env read of RT_FOO_KNOB bypasses" in msgs
    assert "missing from the README knob table" in msgs, msgs


def test_knob_registry_good(tmp_path):
    res = run_fixture(tmp_path, GOOD_KNOBS, [KnobRegistryPass()])
    assert res.ok, messages(res)


def test_knob_registry_dict_key_is_write(tmp_path):
    """An RT_* key in a dict literal (spawn-env mapping for a child) is a
    write-class usage: unregistered names get the register-it message."""
    files = dict(GOOD_KNOBS)
    files["ray_tpu/util/spawn.py"] = """
        def child_env():
            return {"RT_UNKNOWN_CHILD_KNOB": "1"}
    """
    res = run_fixture(tmp_path, files, [KnobRegistryPass()])
    msgs = "\n".join(messages(res))
    assert "RT_UNKNOWN_CHILD_KNOB is not a registered rtconfig flag" in msgs
    files["ray_tpu/util/spawn.py"] = """
        def child_env():
            return {"RT_FOO_KNOB": "1"}
    """
    res = run_fixture(tmp_path, files, [KnobRegistryPass()])
    assert res.ok, messages(res)


def test_knob_registry_allowlist(tmp_path):
    files = dict(GOOD_KNOBS)
    files["ray_tpu/util/boot.py"] = """
        import os

        ADDR = os.environ.get("RT_ADDRESS")
    """
    res = run_fixture(tmp_path, files, [KnobRegistryPass()])
    assert res.ok, messages(res)


# ---------------------------------------------------------- lock-discipline
BAD_LOCKS = {
    "ray_tpu/util/locky.py": """
        import threading

        class Crossed:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def m1(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def m2(self):
                with self._b_lock:
                    with self._a_lock:
                        pass

        class HalfLocked:
            def __init__(self):
                self._lock = threading.Lock()
                self._buf = []
                self._t = threading.Thread(target=self._run, daemon=True)

            def _run(self):
                with self._lock:
                    self._buf = []

            def add(self, item):
                self._buf = self._buf + [item]
    """,
}

GOOD_LOCKS = {
    "ray_tpu/util/locky.py": """
        import threading

        class Ordered:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def m1(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def m2(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

        class FullyLocked:
            def __init__(self):
                self._lock = threading.Lock()
                self._buf = []
                self._t = threading.Thread(target=self._run, daemon=True)

            def _run(self):
                with self._lock:
                    self._buf = []

            def add(self, item):
                with self._lock:
                    self._buf = self._buf + [item]
    """,
}


def test_lock_discipline_bad(tmp_path):
    res = run_fixture(tmp_path, BAD_LOCKS, [LockDisciplinePass()])
    msgs = "\n".join(messages(res))
    assert "lock acquisition cycle" in msgs, msgs
    assert "HalfLocked._buf" in msgs and "without one in `add`" in msgs, msgs


def test_lock_discipline_good(tmp_path):
    res = run_fixture(tmp_path, GOOD_LOCKS, [LockDisciplinePass()])
    assert res.ok, messages(res)


def test_lock_discipline_module_scope(tmp_path):
    # The metrics-flusher / checkpoint-writer shape: module-level lock,
    # module globals, helper thread started from a module function.
    bad = {
        "ray_tpu/util/flushy.py": """
            import threading

            _lock = threading.Lock()
            _pending = []

            def _loop():
                global _pending
                while True:
                    with _lock:
                        _pending = []

            def start():
                threading.Thread(target=_loop, daemon=True).start()

            def add(item):
                global _pending
                _pending = _pending + [item]
        """,
    }
    res = run_fixture(tmp_path, bad, [LockDisciplinePass()])
    msgs = "\n".join(messages(res))
    assert "module global `_pending`" in msgs, msgs

    good = {
        "ray_tpu/util/flushy.py": """
            import threading

            _lock = threading.Lock()
            _pending = []

            def _loop():
                global _pending
                while True:
                    with _lock:
                        _pending = []

            def start():
                threading.Thread(target=_loop, daemon=True).start()

            def add(item):
                global _pending
                with _lock:
                    _pending = _pending + [item]
        """,
    }
    res = run_fixture(tmp_path, good, [LockDisciplinePass()])
    assert res.ok, messages(res)


# ------------------------------------------------------- exception-taxonomy
BAD_EXC = {
    "ray_tpu/exceptions.py": """
        class TaskError(Exception):
            pass
    """,
    "ray_tpu/_private/svc.py": """
        class PrivateWeirdError(Exception):
            pass

        class Svc:
            async def _h_get(self, a):
                raise PrivateWeirdError("off-taxonomy")

        def hot_path():
            try:
                work()
            except:
                pass

        def wedge():
            try:
                work()
            except BaseException:
                pass
    """,
}

GOOD_EXC = {
    "ray_tpu/exceptions.py": """
        class TaskError(Exception):
            pass
    """,
    "ray_tpu/_private/svc.py": """
        from ray_tpu import exceptions as exc

        class Svc:
            async def _h_get(self, a):
                raise exc.TaskError("in taxonomy")

            async def _h_put(self, a):
                raise ValueError("builtins are picklable everywhere")

        def hot_path():
            try:
                work()
            except Exception:
                pass

        def error_blob():
            try:
                work()
            except BaseException as e:  # used: packaged into the blob
                return {"error": repr(e)}
    """,
}


def test_exception_taxonomy_bad(tmp_path):
    res = run_fixture(tmp_path, BAD_EXC, [ExceptionTaxonomyPass()])
    msgs = "\n".join(messages(res))
    assert "bare `except:`" in msgs
    assert "`except BaseException:`" in msgs
    assert "raises PrivateWeirdError" in msgs, msgs


def test_exception_taxonomy_good(tmp_path):
    res = run_fixture(tmp_path, GOOD_EXC, [ExceptionTaxonomyPass()])
    assert res.ok, messages(res)


# ----------------------------------------------------- baseline + machinery
def test_baseline_grandfathers_finding(tmp_path):
    for rel, src in BAD_ASYNC.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    first = core.run(("ray_tpu",), root=str(tmp_path), use_cache=False,
                     baseline_path=str(tmp_path / "none.json"),
                     passes=[AsyncBlockingPass()])
    assert first.findings
    baseline = {"findings": [{"key": f.key, "reason": "grandfathered"}
                             for f in first.findings]}
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps(baseline))
    second = core.run(("ray_tpu",), root=str(tmp_path), use_cache=False,
                      baseline_path=str(bl), passes=[AsyncBlockingPass()])
    assert second.ok
    assert len(second.baselined) == len(first.findings)
    assert not second.stale_baseline


def test_cache_hits_on_unchanged_files(tmp_path, monkeypatch):
    monkeypatch.setenv("RTCHECK_CACHE_DIR", str(tmp_path / "cache"))
    for rel, src in GOOD_ASYNC.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    cold = core.run(("ray_tpu",), root=str(tmp_path),
                    baseline_path=str(tmp_path / "none.json"))
    warm = core.run(("ray_tpu",), root=str(tmp_path),
                    baseline_path=str(tmp_path / "none.json"))
    assert cold.cached_files == 0
    assert warm.cached_files == warm.files == cold.files
    assert warm.ok == cold.ok


def test_duplicate_files_do_not_alias_in_cache(tmp_path, monkeypatch):
    """Byte-identical files each report their own findings at their own
    path (the cache keys by path+sha, not sha alone)."""
    monkeypatch.setenv("RTCHECK_CACHE_DIR", str(tmp_path / "cache"))
    src = """
        def f():
            try:
                g()
            except:
                pass
    """
    files = {"ray_tpu/_private/a.py": src, "ray_tpu/_private/b.py": src}
    for rel, s in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(s))
    res = core.run(("ray_tpu",), root=str(tmp_path),
                   baseline_path=str(tmp_path / "none.json"),
                   passes=[ExceptionTaxonomyPass()])
    assert sorted(f.path for f in res.findings) == [
        "ray_tpu/_private/a.py", "ray_tpu/_private/b.py"]


def test_duplicate_message_keys_get_ordinals(tmp_path):
    """Two identical violations in one file have distinct baseline keys —
    baselining the first must not grandfather a second (or a future third)."""
    files = {
        "ray_tpu/_private/a.py": """
            def f():
                try:
                    g()
                except:
                    pass
                try:
                    h()
                except:
                    pass
        """,
    }
    res = run_fixture(tmp_path, files, [ExceptionTaxonomyPass()])
    keys = [f.key for f in res.findings]
    assert len(keys) == 2 and len(set(keys)) == 2, keys
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps(
        {"findings": [{"key": keys[0], "reason": "grandfathered"}]}))
    res2 = core.run(("ray_tpu",), root=str(tmp_path), use_cache=False,
                    baseline_path=str(bl), passes=[ExceptionTaxonomyPass()])
    assert len(res2.findings) == 1 and len(res2.baselined) == 1


def test_finalize_findings_honor_suppressions(tmp_path):
    """Cross-file (finalize) findings respect inline suppressions at the
    attributed site — e.g. a deliberate wire-arity skew."""
    files = {
        "ray_tpu/_private/proto.py": """
            def encode(x):
                return (x.a, x.b, x.c)  # rtcheck: wire=test.rec

            def decode_prefix(t):
                # reads only the stable prefix, by design
                # rtcheck: disable=wire-schema
                a, b = t  # rtcheck: wire=test.rec
                return a
        """,
    }
    res = run_fixture(tmp_path, files, [WireSchemaPass()])
    assert res.ok, messages(res)


def test_missing_root_is_a_finding(tmp_path):
    """A typo'd analysis root must fail, not silently pass a 0-file run."""
    (tmp_path / "ray_tpu").mkdir()
    res = core.run(("ray_tpu", "prvate_typo"), root=str(tmp_path),
                   use_cache=False,
                   baseline_path=str(tmp_path / "none.json"), passes=[])
    assert not res.ok
    assert any("does not exist" in f.message for f in res.findings)


def test_restricted_roots_stay_clean(tmp_path):
    """`rtcheck ray_tpu/serve` on the real tree must not invent findings
    about files it never scanned (registry/taxonomy anchors come from disk,
    required-wire markers are skipped)."""
    res = core.run(("ray_tpu/serve",), root=REPO_ROOT, use_cache=False)
    assert res.ok, messages(res)


# -------------------------------------------------------------- tier-1 gate
def test_rtcheck_repo_clean_under_budget():
    """The tree itself: zero non-baselined findings, and the whole run —
    cold or warm — fits the 10s tier-1 budget (warm runs are ~10ms via the
    content-hash cache)."""
    t0 = time.monotonic()
    res = core.run(core.DEFAULT_ROOTS, root=REPO_ROOT, use_cache=True)
    elapsed = time.monotonic() - t0
    assert res.ok, "rtcheck findings on the tree:\n" + "\n".join(
        f.render() for f in res.findings)
    assert elapsed < 10.0, f"rtcheck took {elapsed:.1f}s (budget 10s)"
    assert res.files > 100  # sanity: it actually scanned the tree


def test_rtcheck_cli_json():
    """`ray-tpu lint --json` / `python -m tools.rtcheck --json` contract."""
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = core.main(["--json"])
    out = json.loads(buf.getvalue())
    assert rc == 0
    assert out["ok"] is True
    assert out["files"] > 100
    assert isinstance(out["findings"], list)


def test_every_pass_registered():
    ids = {p.id for p in core.all_passes()}
    assert ids == {"async-blocking", "wire-schema", "knob-registry",
                   "lock-discipline", "exception-taxonomy", "event-kinds"}


# -------------------------------------------------------------- event-kinds
_EVENTS_REGISTRY = """
    KINDS = {
        "actor_death": ("error", "an actor is permanently dead"),
        "worker_exit": ("info", "a worker exited"),
    }

    def emit_event(kind, message="", **kw):
        pass

    def build_event(kind, message="", **kw):
        return {"kind": kind}
"""

BAD_EVENT_KINDS = {
    "ray_tpu/_private/events.py": _EVENTS_REGISTRY,
    "ray_tpu/_private/ctl.py": """
        from ray_tpu._private.events import emit_event

        def on_death(self):
            emit_event("actor_detah", "typo'd: unqueryable forever")
            self._emit_event(kind="worker_exti")
    """,
}

GOOD_EVENT_KINDS = {
    "ray_tpu/_private/events.py": _EVENTS_REGISTRY,
    "ray_tpu/_private/ctl.py": """
        from ray_tpu._private.events import emit_event

        def on_death(self, dynamic_kind):
            emit_event("actor_death", "declared kind")
            self._emit_event(kind="worker_exit")
            emit_event(dynamic_kind)  # non-literal: out of scope
    """,
}


def test_event_kinds_bad(tmp_path):
    from tools.rtcheck.passes.event_kinds import EventKindsPass

    res = run_fixture(tmp_path, BAD_EVENT_KINDS, [EventKindsPass()])
    msgs = "\n".join(messages(res))
    assert "'actor_detah'" in msgs and "'worker_exti'" in msgs, msgs
    assert len(res.findings) == 2


def test_event_kinds_good(tmp_path):
    from tools.rtcheck.passes.event_kinds import EventKindsPass

    res = run_fixture(tmp_path, GOOD_EVENT_KINDS, [EventKindsPass()])
    assert res.ok, messages(res)


def test_event_kinds_registry_gone_is_a_finding(tmp_path):
    """Deleting/renaming the KINDS registry while emission sites exist
    must fail loudly, not silently skip the whole check."""
    from tools.rtcheck.passes.event_kinds import EventKindsPass

    files = {
        "ray_tpu/_private/events.py": """
            def emit_event(kind, message="", **kw):
                pass
        """,
        "ray_tpu/_private/ctl.py": """
            from ray_tpu._private.events import emit_event

            def f():
                emit_event("actor_death")
        """,
    }
    res = run_fixture(tmp_path, files, [EventKindsPass()])
    assert any("no declared event kinds" in f.message for f in res.findings)
