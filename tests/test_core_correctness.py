"""Round-2 regression tests for core correctness fixes.

Covers the round-1 advisor/verdict findings: actor call ordering under
concurrent submission (reference sequential_actor_submit_queue.h), kill/
restart idempotency (gcs_actor_manager.cc), pooled-worker env isolation
(worker_pool.h:228), retry_exceptions (task_manager.cc application retries),
cancel (core_worker.proto:492), max_concurrency / async actors
(concurrency_group_manager.h, fiber.h), and lost-object marking
(object_recovery_manager.cc:26).
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu.exceptions import TaskCancelledError, TaskError


def test_actor_ordering_concurrent_burst(ray_start_2cpu):
    """A burst of 200 calls must arrive in submission order even though the
    sends are pipelined (round-1 bug: independent coroutines raced)."""

    @ray_tpu.remote
    class Log:
        def __init__(self):
            self.seen = []

        def add(self, i):
            self.seen.append(i)

        def dump(self):
            return self.seen

    a = Log.remote()
    n = 200
    for i in range(n):
        a.add.remote(i)
    assert ray_tpu.get(a.dump.remote(), timeout=60) == list(range(n))


def test_kill_with_restart_no_double_instance(ray_start_2cpu):
    """kill(no_restart=False) must restart exactly once: the agent's late
    worker_died report for the same instance must be ignored (round-1
    advisor medium: double restart / double resource release)."""

    @ray_tpu.remote
    class Pid:
        def pid(self):
            return os.getpid()

    a = Pid.options(max_restarts=5).remote()
    pid1 = ray_tpu.get(a.pid.remote(), timeout=30)
    ray_tpu.kill(a, no_restart=False)
    # Wait for the RESTARTED instance to answer. A call racing the kill can
    # still reach the old, not-yet-dead instance and echo pid1 — that's the
    # kill's asynchrony, not a restart failure — so keep polling until a
    # different pid answers (deflake: pid1 on the first post-kill call flipped
    # this test whenever suite timing shifted).
    deadline = time.time() + 30
    pid2 = None
    while time.time() < deadline:
        try:
            got = ray_tpu.get(a.pid.remote(), timeout=10)
            if got != pid1:
                pid2 = got
                break
        except Exception:
            pass
        time.sleep(0.2)
    assert pid2 is not None and pid2 != pid1
    # Let any stale worker_died report land, then verify: exactly 1 restart
    # consumed and resources not double-released (available <= total).
    time.sleep(1.0)
    from ray_tpu.util import state

    (actor_info,) = state.list_actors()
    assert actor_info["restarts_used"] == 1
    res = ray_tpu._require_worker().cluster_resources()
    assert res["available"].get("CPU", 0) <= res["total"].get("CPU", 0)


def test_pooled_worker_env_isolation(ray_start_2cpu):
    """A task's env_vars must not leak into the next task on a reused pool
    worker (round-1 bug: os.environ.update was permanent)."""

    @ray_tpu.remote
    def read_env(k):
        return os.environ.get(k)

    r1 = read_env.options(runtime_env={"env_vars": {"RT_TEST_LEAK": "yes"}}).remote("RT_TEST_LEAK")
    assert ray_tpu.get(r1, timeout=30) == "yes"
    # Subsequent tasks without that env var must not observe it.
    vals = ray_tpu.get([read_env.remote("RT_TEST_LEAK") for _ in range(4)], timeout=30)
    assert all(v is None for v in vals)


def test_retry_exceptions_true(ray_start_2cpu):
    """retry_exceptions=True retries user exceptions up to max_retries."""

    @ray_tpu.remote
    class Count:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    counter = Count.remote()

    @ray_tpu.remote(max_retries=3, retry_exceptions=True)
    def flaky(c):
        n = ray_tpu.get(c.bump.remote(), timeout=10)
        if n < 3:
            raise ValueError(f"attempt {n} fails")
        return n

    assert ray_tpu.get(flaky.remote(counter), timeout=60) == 3


def test_retry_exceptions_off_is_final(ray_start_2cpu):
    @ray_tpu.remote(max_retries=3)
    def boom():
        raise ValueError("no retry")

    with pytest.raises(TaskError):
        ray_tpu.get(boom.remote(), timeout=30)


def test_retry_exceptions_type_filter(ray_start_2cpu):
    """A list of exception types only retries matching exceptions."""

    @ray_tpu.remote(max_retries=2, retry_exceptions=[KeyError])
    def wrong_type():
        raise ValueError("not in the retry list")

    with pytest.raises(TaskError):
        ray_tpu.get(wrong_type.remote(), timeout=30)


def test_cancel_running_task(ray_start_2cpu):
    @ray_tpu.remote
    def sleeper():
        time.sleep(60)
        return "never"

    ref = sleeper.remote()
    time.sleep(1.0)  # let it start
    ray_tpu.cancel(ref)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=30)


def test_cancel_pending_task(ray_start_2cpu):
    @ray_tpu.remote
    def hog():
        time.sleep(30)

    @ray_tpu.remote
    def queued():
        return 1

    # Saturate both CPUs, then queue one more and cancel it before dispatch.
    hogs = [hog.remote() for _ in range(2)]
    time.sleep(0.5)
    ref = queued.remote()
    ray_tpu.cancel(ref)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=30)
    for h in hogs:
        ray_tpu.cancel(h, force=True)


def test_threaded_actor_max_concurrency(ray_start_4cpu):
    """max_concurrency>1 runs calls concurrently in the actor process."""

    @ray_tpu.remote(max_concurrency=4)
    class Slow:
        def wait(self, t):
            time.sleep(t)
            return os.getpid()

    a = Slow.remote()
    ray_tpu.get(a.wait.remote(0.0), timeout=60)  # wait for actor startup
    t0 = time.time()
    pids = ray_tpu.get([a.wait.remote(1.0) for _ in range(4)], timeout=60)
    elapsed = time.time() - t0
    assert len(set(pids)) == 1  # same process
    assert elapsed < 3.0  # ran concurrently, not 4s serially


def test_async_actor(ray_start_2cpu):
    """Coroutine methods run on the actor's asyncio loop, concurrently."""
    import asyncio

    @ray_tpu.remote(max_concurrency=8)
    class Async:
        async def wait_id(self, i, t):
            await asyncio.sleep(t)
            return i

    a = Async.remote()
    ray_tpu.get(a.wait_id.remote(-1, 0.0), timeout=60)  # wait for actor startup
    t0 = time.time()
    out = ray_tpu.get([a.wait_id.remote(i, 1.0) for i in range(6)], timeout=60)
    elapsed = time.time() - t0
    assert out == list(range(6))
    assert elapsed < 4.0
