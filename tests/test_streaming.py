"""Streaming generators: num_returns="streaming" -> ObjectRefGenerator.

Parity target: reference streaming-generator semantics
(src/ray/protobuf/core_worker.proto:478 ReportGeneratorItemReturns;
python/ray/_raylet.pyx ObjectRefGenerator): items are reported to the owner
incrementally as the executing generator yields them, with consumer-driven
backpressure, mid-stream cancellation, partial consumption GC, and retry of
a generator task whose worker died mid-stream.
"""

import os
import sys
import tempfile
import time

import numpy as np
import pytest

import ray_tpu


def test_task_generator_basic(ray_start_2cpu):
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * 10

    g = gen.remote(5)
    assert isinstance(g, ray_tpu.ObjectRefGenerator)
    vals = [ray_tpu.get(ref) for ref in g]
    assert vals == [0, 10, 20, 30, 40]
    # completed() resolves to the item count
    assert ray_tpu.get(g.completed()) == 5


def test_generator_items_arrive_before_completion(ray_start_2cpu):
    """Items are consumable while the generator is still running — the
    defining property vs num_returns=N."""

    @ray_tpu.remote(num_returns="streaming")
    def slow_gen():
        yield "first"
        time.sleep(5)
        yield "second"

    g = slow_gen.remote()
    t0 = time.monotonic()
    first = ray_tpu.get(next(g))
    first_latency = time.monotonic() - t0
    assert first == "first"
    # The first item must arrive long before the 5s second item.
    assert first_latency < 3.0
    assert ray_tpu.get(next(g)) == "second"
    with pytest.raises(StopIteration):
        next(g)


def test_generator_large_items_and_mixed_sizes(ray_start_2cpu):
    @ray_tpu.remote(num_returns="streaming")
    def gen():
        yield 1  # inline
        yield np.ones((512, 512), np.float32)  # shm path (1MB)
        yield "tail"

    g = gen.remote()
    assert ray_tpu.get(next(g)) == 1
    arr = ray_tpu.get(next(g))
    assert arr.shape == (512, 512) and float(arr.sum()) == 512 * 512
    assert ray_tpu.get(next(g)) == "tail"


def test_generator_midstream_error(ray_start_2cpu):
    """The error surfaces after the last good item (reference: the exception
    is the item at the failing index)."""

    @ray_tpu.remote(num_returns="streaming", max_retries=0)
    def bad():
        yield 1
        yield 2
        raise ValueError("boom at index 2")

    g = bad.remote()
    assert ray_tpu.get(next(g)) == 1
    assert ray_tpu.get(next(g)) == 2
    with pytest.raises(ray_tpu.exceptions.TaskError, match="boom"):
        next(g)


def test_generator_consume_partial_then_drop(ray_start_2cpu):
    """Dropping a partially-consumed generator frees the unconsumed items
    and does not wedge anything."""

    @ray_tpu.remote(num_returns="streaming")
    def gen():
        for i in range(20):
            yield np.ones(200_000, np.uint8)  # shm-sized items

    g = gen.remote()
    first = ray_tpu.get(next(g))
    assert first.nbytes == 200_000
    tid = g.task_id
    del g  # destroys the stream; unconsumed items freed, task cancelled
    w = ray_tpu._private.worker.global_worker()
    deadline = time.monotonic() + 10
    while tid in w._generators and time.monotonic() < deadline:
        time.sleep(0.05)
    assert tid not in w._generators
    # cluster still healthy
    @ray_tpu.remote
    def ping():
        return "ok"

    assert ray_tpu.get(ping.remote()) == "ok"


def test_generator_cancel_midstream(ray_start_2cpu):
    @ray_tpu.remote(num_returns="streaming", max_retries=0)
    def forever():
        i = 0
        while True:
            yield i
            i += 1
            time.sleep(0.05)

    g = forever.remote()
    assert ray_tpu.get(next(g)) == 0
    ray_tpu.cancel(g)
    with pytest.raises(
            (ray_tpu.exceptions.TaskCancelledError, StopIteration,
             ray_tpu.exceptions.TaskError)):
        # drain until the cancellation surfaces (a few items may already be
        # in flight)
        for _ in range(10_000):
            next(g)


def test_generator_backpressure(ray_start_2cpu):
    """Producer pauses once generator_backpressure_items are unacked: a
    slow consumer must observe a bounded production lead."""

    @ray_tpu.remote
    class Probe:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1

        def count(self):
            return self.n

    probe = Probe.remote()

    @ray_tpu.remote(num_returns="streaming")
    def gen(probe):
        for i in range(300):
            probe.bump.remote()
            yield i

    g = gen.remote(probe)
    # consume two items slowly, then check the producer didn't run away
    assert ray_tpu.get(next(g)) == 0
    time.sleep(1.0)
    produced = ray_tpu.get(probe.count.remote())
    # backpressure threshold is 64; allow slack for the ack stride + pipeline
    assert produced < 200, f"producer ran {produced} items ahead"
    vals = [ray_tpu.get(r) for r in g]
    assert vals == list(range(1, 300))


def test_generator_task_retry_on_worker_death(ray_start_2cpu, tmp_path):
    """Worker dies mid-stream -> lease requeue re-executes the generator;
    re-reported indices dedup at the owner and the consumer sees the full
    stream exactly once."""
    marker = str(tmp_path / "died_once")

    @ray_tpu.remote(num_returns="streaming", max_retries=2)
    def flaky(marker):
        for i in range(6):
            if i == 3 and not os.path.exists(marker):
                open(marker, "w").close()
                os._exit(1)  # simulated worker crash mid-stream
            yield i

    g = flaky.remote(marker)
    vals = [ray_tpu.get(r) for r in g]
    assert vals == [0, 1, 2, 3, 4, 5]
    assert ray_tpu.get(g.completed()) == 6


def test_actor_sync_generator_method(ray_start_2cpu):
    @ray_tpu.remote
    class Streamer:
        def tokens(self, n):
            for i in range(n):
                yield f"tok{i}"

    s = Streamer.remote()
    g = s.tokens.options(num_returns="streaming").remote(4)
    assert isinstance(g, ray_tpu.ObjectRefGenerator)
    assert [ray_tpu.get(r) for r in g] == ["tok0", "tok1", "tok2", "tok3"]


def test_actor_async_generator_method(ray_start_2cpu):
    @ray_tpu.remote
    class AsyncStreamer:
        async def tokens(self, n):
            import asyncio

            for i in range(n):
                await asyncio.sleep(0.001)
                yield i * i

    s = AsyncStreamer.remote()
    g = s.tokens.options(num_returns="streaming").remote(5)
    assert [ray_tpu.get(r) for r in g] == [0, 1, 4, 9, 16]


def test_actor_stream_abandoned_does_not_wedge_actor(ray_start_2cpu):
    """Dropping a partially-consumed ACTOR stream must stop the producer
    (gen_close) and free the actor's execution slot — there is no
    lease/controller cancel path for actor tasks."""

    @ray_tpu.remote
    class Streamer:
        def stream(self):
            for i in range(10_000):
                yield np.ones(1000, np.uint8)

        def ping(self):
            return "alive"

    s = Streamer.remote()
    g = s.stream.options(num_returns="streaming").remote()
    assert ray_tpu.get(next(g)).nbytes == 1000
    del g  # abandon: backpressure would otherwise park the producer forever
    # A max_concurrency=1 actor must serve the next call promptly.
    assert ray_tpu.get(s.ping.remote()) == "alive"


def test_method_decorator_streaming(ray_start_2cpu):
    @ray_tpu.remote
    class S:
        @ray_tpu.method(num_returns="streaming")
        def stream(self):
            yield "a"
            yield "b"

    s = S.remote()
    g = s.stream.remote()
    assert [ray_tpu.get(r) for r in g] == ["a", "b"]


def test_generator_items_passable_to_tasks(ray_start_2cpu):
    """Yielded refs are first-class objects: pass one to another task."""

    @ray_tpu.remote(num_returns="streaming")
    def gen():
        yield np.arange(10)
        yield np.arange(5)

    @ray_tpu.remote
    def total(x):
        return int(x.sum())

    g = gen.remote()
    r1 = next(g)
    assert ray_tpu.get(total.remote(r1)) == 45


def test_streaming_rejects_tpu_tasks(ray_start_2cpu):
    @ray_tpu.remote(num_returns="streaming", num_tpus=1)
    def gen():
        yield 1

    with pytest.raises(ValueError, match="streaming"):
        gen.remote()
