"""Production LLM serving: continuous batching engine + OpenAI surface.

Parity target: reference python/ray/llm/_internal/serve — vLLM engine seat
(continuous batching, sampling, streaming) + OpenAI-compatible router
(routers/router.py) + build_openai_app (application_builders.py).
"""

import json
import time
import urllib.request

import numpy as np
import pytest

import ray_tpu
from ray_tpu.llm import LLMConfig
from ray_tpu.llm.engine import ContinuousEngine, SamplingParams

CFG = LLMConfig(vocab_size=384, d_model=64, n_layers=2, n_heads=4,
                max_seq=128)


@pytest.fixture(scope="module")
def engine():
    eng = ContinuousEngine(CFG, max_batch=4, decode_chunk=4)
    yield eng
    eng.shutdown()


def test_engine_greedy_deterministic(engine):
    a = engine.submit([1, 2, 3], SamplingParams(temperature=0.0,
                                                max_tokens=6)).tokens()
    b = engine.submit([1, 2, 3], SamplingParams(temperature=0.0,
                                                max_tokens=6)).tokens()
    assert a == b and len(a) == 6


def test_engine_no_lockstep(engine):
    """Requests of different lengths complete independently — the defining
    property of continuous batching vs whole-batch generate()."""
    # 120 tokens (30 decode chunks): wide enough that the consumer thread
    # reliably observes the long request still active right after the short
    # one drains, even when a loaded CI box deschedules it for a while.
    # Anchor the short submit on the long request's FIRST token rather
    # than a wall-clock sleep: the pipelined hot loop decodes the whole
    # 120 fast enough that a fixed sleep could eat its entire lifetime.
    long_s = engine.submit([5, 6, 7], SamplingParams(temperature=0.0,
                                                     max_tokens=120))
    first_long = long_s.next(timeout=60)
    t0 = time.monotonic()
    short = engine.submit([8, 9], SamplingParams(temperature=0.0,
                                                 max_tokens=3)).tokens()
    short_done = time.monotonic() - t0
    # the long request must still be in flight when the short one finished
    assert engine.num_active >= 1
    assert len(short) == 3
    long_toks = [first_long] + long_s.tokens()
    assert len(long_toks) == 120
    assert short_done < 30.0


def test_engine_join_running_batch(engine):
    """A request submitted mid-decode joins the running batch (its first
    token arrives long before the in-flight request finishes)."""
    long_s = engine.submit([1], SamplingParams(temperature=0.0,
                                               max_tokens=80))
    # wait until the long request has produced a few tokens
    first_long = long_s.next(timeout=60)
    joiner = engine.submit([2, 3], SamplingParams(temperature=0.0,
                                                  max_tokens=4))
    first_join = joiner.next(timeout=60)
    assert isinstance(first_long, int) and isinstance(first_join, int)
    # long request still active after the joiner got its first token
    assert engine.num_active >= 1
    joiner.tokens()
    long_s.tokens()


def test_engine_sampling_modes(engine):
    greedy = engine.submit([1, 2, 3], SamplingParams(
        temperature=0.0, max_tokens=8)).tokens()
    topk1 = engine.submit([1, 2, 3], SamplingParams(
        temperature=1.0, top_k=1, max_tokens=8)).tokens()
    assert topk1 == greedy  # top_k=1 collapses to greedy
    hot1 = engine.submit([1, 2, 3], SamplingParams(
        temperature=8.0, max_tokens=16, seed=11)).tokens()
    hot2 = engine.submit([1, 2, 3], SamplingParams(
        temperature=8.0, max_tokens=16, seed=22)).tokens()
    assert hot1 != hot2  # high temperature + different seeds diverge
    capped = engine.submit([1, 2, 3], SamplingParams(
        temperature=8.0, top_p=1e-9, max_tokens=8, seed=5)).tokens()
    assert capped == greedy  # tiny top_p keeps only the argmax token


def test_engine_stop_token(engine):
    base = engine.submit([4, 5], SamplingParams(
        temperature=0.0, max_tokens=12)).tokens()
    stop = base[3]
    s = engine.submit([4, 5], SamplingParams(
        temperature=0.0, max_tokens=12, stop_token=int(stop)))
    toks = s.tokens()
    assert toks[-1] == stop and len(toks) == 4
    assert s.finish_reason == "stop"


def test_engine_overflow_rejected(engine):
    with pytest.raises(ValueError, match="max_seq"):
        engine.submit(list(range(100)), SamplingParams(max_tokens=100))


def test_serve_openai_http(ray_start_4cpu):
    """End-to-end: OpenAI app over HTTP — models list, completion, and SSE
    token streaming (tokens must ARRIVE incrementally)."""
    from ray_tpu import serve
    from ray_tpu.llm.openai import build_openai_app

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    app = build_openai_app(CFG, model_id="test-llm", max_batch=4,
                           decode_chunk=4, default_max_tokens=8)
    serve.run(app, route_prefix="/", port=port)
    try:
        base = f"http://127.0.0.1:{port}"
        # /v1/models
        with urllib.request.urlopen(f"{base}/v1/models", timeout=30) as r:
            models = json.loads(r.read())
        assert models["data"][0]["id"] == "test-llm"
        # non-streaming completion
        body = json.dumps({"prompt": "hi", "max_tokens": 5,
                           "temperature": 0.0}).encode()
        req = urllib.request.Request(
            f"{base}/v1/completions", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            out = json.loads(r.read())
        assert out["object"] == "text_completion"
        assert len(out["token_ids"]) == 5
        assert out["choices"][0]["finish_reason"] == "length"
        # streaming completion (SSE)
        body = json.dumps({"prompt": "hi", "max_tokens": 6,
                           "temperature": 0.0, "stream": True}).encode()
        req = urllib.request.Request(
            f"{base}/v1/completions", data=body,
            headers={"Content-Type": "application/json"})
        chunks, arrival = [], []
        with urllib.request.urlopen(req, timeout=120) as r:
            for line in r:
                line = line.decode().strip()
                if not line.startswith("data: "):
                    continue
                payload = line[len("data: "):]
                arrival.append(time.monotonic())
                if payload == "[DONE]":
                    chunks.append(None)
                    break
                chunks.append(json.loads(payload))
        assert chunks[-1] is None  # [DONE] terminator
        deltas = [c for c in chunks[:-1] if c]
        # 6 token chunks + 1 finish chunk
        toks = [t for c in deltas for t in c.get("token_ids", [])]
        assert len(toks) == 6
        assert deltas[-1]["choices"][0]["finish_reason"] == "length"
        # chat form
        body = json.dumps({"messages": [{"role": "user", "content": "yo"}],
                           "max_tokens": 4, "temperature": 0.0}).encode()
        req = urllib.request.Request(
            f"{base}/v1/chat/completions", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            out = json.loads(r.read())
        assert out["object"] == "chat.completion"
        assert out["choices"][0]["message"]["role"] == "assistant"
    finally:
        serve.shutdown()


def test_serve_handle_streaming(ray_start_2cpu):
    """Python-side handle streaming: handle.options(stream=True) yields
    refs incrementally from a generator deployment method."""
    from ray_tpu import serve

    @serve.deployment
    class Counter:
        def counted(self, n):
            for i in range(n):
                yield {"i": i}

    serve.run(Counter.bind(), route_prefix="/counter")
    try:
        h = serve.get_deployment_handle("Counter")
        gen = h.counted.options(stream=True).remote(5)
        vals = [ray_tpu.get(ref)["i"] for ref in gen]
        assert vals == [0, 1, 2, 3, 4]
    finally:
        serve.shutdown()
