"""Chaos tests for the stall-detection plane (README "Stall detection &
watchdogs"): silent hangs must become fast, attributed failures.

Pinned here:
- a stalled task walks the warn -> dump -> kill escalation ladder, its
  flight dump survives in storage, and the RETRY completes exactly once;
- a collective wedged on a sick peer aborts with CollectiveTimeoutError
  naming the op, group, and peer — never hangs the suite;
- @remote(timeout_s=) interrupts a runaway attempt worker-side and retries
  it under max_retries as a system failure (TaskTimeoutError when spent);
- get(timeout=) on a still-pending object names the producing task's
  status instead of a bare timeout;
- a train group that stops reporting restarts elastically from the latest
  COMMITTED checkpoint;
- with every RT_STALL_* stage unset, nothing beacons and nothing reports —
  escalation off is byte-identical.
"""

import os
import pickle
import tempfile
import time

import pytest

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu.util import state


def _attempt_marker():
    """Cross-process attempt counter: returns (path, bump) where bump()
    increments and returns the pre-increment count."""
    path = tempfile.mktemp(prefix="rt_stall_marker_")
    return path


@ray_tpu.remote(max_retries=2)
def stalls_on_first_attempt(path):
    import os
    import time as _t

    n = int(open(path).read()) if os.path.exists(path) else 0
    with open(path, "w") as f:
        f.write(str(n + 1))
    if n == 0:
        _t.sleep(120)  # silent stall: alive, socket open, no progress
    return n + 1


def test_stalled_task_escalates_warn_dump_kill_and_retries(shutdown_only):
    ray_tpu.init(num_cpus=2, _system_config={
        "stall_warn_s": 0.6,
        "stall_dump_s": 1.1,
        "stall_kill_s": 1.8,
        "stall_beacon_interval_s": 0.2,
    })
    marker = _attempt_marker()
    t0 = time.monotonic()
    out = ray_tpu.get(stalls_on_first_attempt.remote(marker), timeout=60)
    elapsed = time.monotonic() - t0
    # The retry ran EXACTLY once: first attempt stalled and was killed,
    # second returned 2; a third run would have written 3.
    assert out == 2
    time.sleep(0.3)
    assert open(marker).read() == "2"
    # The stalled get resolved via the kill + retry, not a 120s sleep.
    assert elapsed < 30
    stalls = state.list_stalls()
    by_stage = {s["stage"] for s in stalls
                if s.get("name") == "stalls_on_first_attempt"}
    assert {"warn", "dump", "kill"} <= by_stage, stalls
    dump = next(s for s in stalls if s["stage"] == "dump"
                and s.get("name") == "stalls_on_first_attempt")
    # Dump-stage escalation captured live stacks through the agent's
    # per-pid machinery and persisted the flight dump through storage.
    assert dump.get("stacks"), "no stack capture on dump escalation"
    assert "sleep" in dump["stacks"] or "stalls_on_first" in dump["stacks"]
    assert dump.get("flight_path") and os.path.exists(dump["flight_path"])
    # The persisted dump carries the flight-recorder ring.
    import json

    persisted = json.loads(open(dump["flight_path"]).read())
    assert persisted["stage"] == "dump"
    assert isinstance(persisted.get("events"), list)
    # Escalations are counted per stage.
    mets = {(m["name"], m["tags"].get("stage")): m["value"]
            for m in state.metrics() if m["name"] == "rt_stalls_total"}
    assert mets.get(("rt_stalls_total", "kill"), 0) >= 1
    assert mets.get(("rt_stalls_total", "warn"), 0) >= 1


def test_stalls_cli_lists_reports(shutdown_only):
    ray_tpu.init(num_cpus=1, _system_config={
        "stall_warn_s": 0.4, "stall_kill_s": 1.2,
        "stall_beacon_interval_s": 0.1,
    })
    marker = _attempt_marker()
    assert ray_tpu.get(stalls_on_first_attempt.remote(marker), timeout=60) == 2

    from ray_tpu.scripts.cli import main as cli_main

    host, port = ray_tpu._head.controller_addr
    rc = cli_main(["stalls", "--address", f"{host}:{port}", "--verbose"])
    assert rc == 0


@ray_tpu.remote(timeout_s=0.6, max_retries=1)
def slow_then_fast(path):
    import os
    import time as _t

    n = int(open(path).read()) if os.path.exists(path) else 0
    with open(path, "w") as f:
        f.write(str(n + 1))
    if n == 0:
        _t.sleep(60)
    return "done"


@ray_tpu.remote(timeout_s=0.5, max_retries=0)
def always_slow():
    import time as _t

    _t.sleep(60)


def test_task_timeout_s_retries_then_surfaces(shutdown_only):
    ray_tpu.init(num_cpus=2)
    # Attempt 0 blows its per-attempt deadline -> retried as a system
    # failure -> attempt 1 returns.
    marker = _attempt_marker()
    t0 = time.monotonic()
    assert ray_tpu.get(slow_then_fast.remote(marker), timeout=30) == "done"
    assert time.monotonic() - t0 < 20
    assert open(marker).read() == "2"
    # Retries spent -> TaskTimeoutError reaches the caller.
    with pytest.raises(exc.TaskTimeoutError, match="per-attempt timeout"):
        ray_tpu.get(always_slow.remote(), timeout=30)


def test_get_timeout_names_producing_task(shutdown_only):
    ray_tpu.init(num_cpus=1)

    @ray_tpu.remote
    def napper():
        import time as _t

        _t.sleep(8)
        return 1

    ref = napper.remote()
    time.sleep(0.3)
    with pytest.raises(exc.GetTimeoutError) as ei:
        ray_tpu.get(ref, timeout=0.5)
    msg = str(ei.value)
    assert "napper" in msg, msg
    assert "running" in msg or "queued" in msg, msg
    assert ray_tpu.get(ref, timeout=30) == 1


def test_collective_timeout_names_op_group_peer(shutdown_only):
    ray_tpu.init(num_cpus=2, _system_config={"collective_timeout_s": 2.0})

    @ray_tpu.remote
    class Rank:
        def __init__(self, rank):
            self.rank = rank

        def join(self, world):
            from ray_tpu.util import collective

            collective.init_collective_group(world, self.rank, "wedge")
            return True

        def reduce(self):
            import numpy as np

            from ray_tpu.util import collective

            return collective.allreduce(np.ones(8), group_name="wedge")

        def sit(self):
            import time as _t

            _t.sleep(60)

    a, b = Rank.remote(0), Rank.remote(1)
    assert ray_tpu.get([a.join.remote(2), b.join.remote(2)], timeout=60)
    b.sit.remote()  # rank 1 wedges instead of joining the allreduce
    t0 = time.monotonic()
    with pytest.raises(exc.TaskError) as ei:
        ray_tpu.get(a.reduce.remote(), timeout=30)
    elapsed = time.monotonic() - t0
    msg = str(ei.value)
    # Aborted within the configured deadline (plus slack), never hanging
    # the suite; the error names op, group, and the wedged peer.
    assert elapsed < 15
    assert "CollectiveTimeoutError" in msg
    assert "allreduce" in msg and "wedge" in msg and "peer rank 1" in msg
    assert isinstance(ei.value.cause, exc.CollectiveTimeoutError)


def _stall_train_loop(config):
    import ray_tpu.train as train

    ckpt = train.get_checkpoint()
    if ckpt is not None:  # restarted attempt: resume from the commit
        with open(os.path.join(ckpt.path, "state.pkl"), "rb") as f:
            saved = pickle.load(f)
        train.report({"step": saved["step"] + 1, "resumed": 1})
        return
    with tempfile.TemporaryDirectory() as d:
        with open(os.path.join(d, "state.pkl"), "wb") as f:
            pickle.dump({"step": 1}, f)
        train.report({"step": 1}, checkpoint=train.Checkpoint(d))
    time.sleep(120)  # silent group stall: alive, no reports, no crash


def test_train_group_stall_restarts_from_committed_checkpoint(shutdown_only):
    from ray_tpu.train import (
        FailureConfig,
        JaxTrainer,
        RunConfig,
        ScalingConfig,
    )

    ray_tpu.init(num_cpus=2)
    with tempfile.TemporaryDirectory() as storage_dir:
        trainer = JaxTrainer(
            _stall_train_loop,
            train_loop_config={},
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(
                name="stall_run",
                storage_path=storage_dir,
                failure_config=FailureConfig(
                    max_failures=2, stall_timeout_s=2.5),
            ),
        )
        t0 = time.monotonic()
        result = trainer.fit()
        elapsed = time.monotonic() - t0
        assert result.error is None, result.error
        # Second attempt resumed from the checkpoint the first committed.
        assert result.metrics.get("resumed") == 1
        assert result.metrics.get("step") == 2
        assert elapsed < 90
        # The group stall surfaced through the cluster stall plane.
        rows = [s for s in state.list_stalls()
                if s.get("scope") == "train_group"]
        assert rows and rows[0]["stage"] == "kill"


def test_escalation_disabled_is_inert(shutdown_only):
    """No RT_STALL_* stage set: the watchdog never starts, nothing beacons,
    nothing reports — a slow task is just a slow task."""
    ray_tpu.init(num_cpus=1)

    @ray_tpu.remote
    def slowish():
        import time as _t

        _t.sleep(1.2)
        return "ok"

    assert ray_tpu.get(slowish.remote(), timeout=30) == "ok"
    assert state.list_stalls() == []
    assert not any(m["name"] == "rt_stalls_total" for m in state.metrics())
    # No beacon state ever reached the controller either.
    assert ray_tpu._head.controller._task_beacons == {}
