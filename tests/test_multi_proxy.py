"""Multi-proxy fan-out (README "Cross-host streaming & multi-proxy"):
N proxy processes share one replica fleet via the controller's routing,
each with its own admission queues against the shared budgets.

Pins the fleet contract end to end: scale-out on a later serve.run, the
same bytes through every proxy, /v1/stats aggregation across the fleet
(single-proxy response shape untouched), the replica-side concurrency
cap as the shared admission backstop at N>1, and the chaos story — a
SIGKILLed proxy fails ITS clients fast while the survivor's streams run
uninterrupted, and a later serve.run rejoins a fresh proxy under the
same name once the controller marks the old actor dead.
"""

import json
import os
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve

CFG_KW = dict(vocab_size=384, d_model=64, n_layers=2, n_heads=4,
              max_seq=256)


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _llm_app(**kw):
    from ray_tpu.llm import LLMConfig
    from ray_tpu.llm.openai import build_openai_app

    return build_openai_app(LLMConfig(**CFG_KW), max_batch=4,
                            decode_chunk=4, **kw)


def _sse_tokens(port, max_tokens, on_first=None, timeout=120):
    """Streamed completion via one proxy. Returns (token_ids, error):
    error is the structured SSE error event if one arrived, or
    "connection dropped" when the stream ended without its [DONE]
    terminator (a dead proxy can only drop the socket — the missing
    terminator IS the client-visible failure signal)."""
    body = json.dumps({"model": "m", "prompt": "the quick brown",
                       "max_tokens": max_tokens, "stream": True,
                       "temperature": 0.0}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/completions", data=body,
        headers={"Content-Type": "application/json"})
    toks = []
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        for line in resp:
            line = line.decode().strip()
            if not line.startswith("data: "):
                continue
            data = line[6:]
            if data == "[DONE]":
                return toks, None
            ev = json.loads(data)
            if "error" in ev:
                return toks, ev["error"]
            toks.extend(ev.get("token_ids", []) or [])
            if on_first is not None:
                on_first.set()
    return toks, "connection dropped"


def _stats(port):
    return json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/v1/stats", timeout=30).read())


def test_fleet_scale_out_sigkill_and_rejoin(shutdown_only):
    """The fleet lifecycle end to end, one cluster. Scale-out:
    serve.run(num_proxies=1) then the SAME app at 2 proxies — proxy 0
    keeps its port, the extra auto-binds and registers, both serve
    byte-identical greedy streams, /v1/stats aggregates the fleet while
    the single-proxy response shape stays exactly as before (no
    serve_proxies key). Chaos: SIGKILL one proxy mid-SSE — its clients
    fail fast at the HTTP layer (the dead proxy can't write — a
    transport error, never a hang), the survivor's streams finish
    byte-complete, and a later serve.run rejoins a fresh proxy under the
    same name via the controller's DEAD-actor name reuse."""
    from ray_tpu.serve._private.controller import CONTROLLER_NAME

    ray_tpu.init(num_cpus=4)
    port = _free_port()
    app = _llm_app()
    serve.run(app, port=port, num_proxies=1)

    single = _stats(port)
    assert "serve" in single
    assert "serve_proxies" not in single, (
        "single-proxy /v1/stats grew a fleet key — shape must stay "
        "byte-identical")
    assert serve.proxy_ports() == {"_serve_proxy": port}

    serve.run(app, port=port, num_proxies=2)
    ports = serve.proxy_ports()
    assert len(ports) == 2 and ports["_serve_proxy"] == port
    victim = next(n for n in ports if n != "_serve_proxy")
    extra = ports[victim]
    assert extra != port

    toks0, err0 = _sse_tokens(port, 32)
    toks1, err1 = _sse_tokens(extra, 32)
    assert err0 is None and err1 is None
    assert len(toks0) == 32
    assert toks1 == toks0, "proxies disagreed on a greedy decode"

    agg = _stats(port)
    assert "serve_proxies" in agg and len(agg["serve_proxies"]) == 2
    for name, snap in agg["serve_proxies"].items():
        assert "pid" in snap and "active_streams" in snap, (name, snap)
    assert "serve" in agg

    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    reg = ray_tpu.get(controller.list_proxies.remote(), timeout=10)
    victim_pid = reg[victim]["pid"]

    outcomes = {}
    started = threading.Event()

    def survivor():
        outcomes["survivor"] = _sse_tokens(port, 64)

    def victim_client():
        try:
            outcomes["victim"] = ("done", _sse_tokens(
                extra, 64, on_first=started))
        except Exception as e:
            outcomes["victim"] = ("failed", repr(e), time.monotonic())

    ts = [threading.Thread(target=survivor, daemon=True),
          threading.Thread(target=victim_client, daemon=True)]
    for t in ts:
        t.start()
    assert started.wait(timeout=60), "victim stream never started"
    t_kill = time.monotonic()
    os.kill(victim_pid, 9)
    for t in ts:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in ts), "a client hung after the kill"

    # The dead proxy's client fails at the transport layer, fast.
    kind = outcomes["victim"][0]
    if kind == "done":
        # Either the stream raced to completion before the kill landed,
        # or the drop was visible — a missing [DONE]/an error event.
        toks, err = outcomes["victim"][1]
        assert err is not None or len(toks) == 64
    else:
        t_fail = outcomes["victim"][2]
        assert t_fail - t_kill < 15.0, (
            f"victim client took {t_fail - t_kill:.1f}s "
            f"after the kill to fail")
    # The survivor never noticed.
    toks, err = outcomes["survivor"]
    assert err is None and len(toks) == 64, (len(toks), err)

    # Rejoin: the controller must first mark the killed actor DEAD, then
    # the same serve.run re-creates the proxy under the same name.
    deadline = time.monotonic() + 45
    rejoined = False
    while time.monotonic() < deadline and not rejoined:
        try:
            serve.run(app, port=port, num_proxies=2)
            rejoined = True
        except Exception:
            time.sleep(1.0)
    assert rejoined, "serve.run could not rejoin a proxy within 45s"
    new_ports = serve.proxy_ports()
    assert victim in new_ports
    toks, err = _sse_tokens(new_ports[victim], 16)
    assert err is None and len(toks) == 16, (
        "rejoined proxy not serving streams")
    serve.shutdown()


def test_admission_backstop_across_proxies(shutdown_only):
    """A storm split across BOTH proxies against one capped replica: each
    proxy runs its own admission queue, the replica-side concurrency cap
    is the shared backstop. Every client resolves — 200, or typed
    429/503 JSON within the queue deadline. Zero bare 500s, zero
    hangs."""
    ray_tpu.init(num_cpus=4)

    @serve.deployment(max_ongoing_requests=4, max_queued_requests=4,
                      queue_deadline_s=1.5,
                      ray_actor_options={"num_cpus": 0.5})
    class Work:
        def __call__(self, request=None):
            time.sleep(0.4)
            return {"pid": os.getpid()}

    port = _free_port()
    serve.run(Work.bind(), port=port, num_proxies=2)
    ports = list(serve.proxy_ports().values())
    assert len(ports) == 2

    results = []
    lock = threading.Lock()

    def client(i):
        url = f"http://127.0.0.1:{ports[i % 2]}/"
        t0 = time.monotonic()
        try:
            body = urllib.request.urlopen(url, timeout=30).read()
            out = (200, json.loads(body), time.monotonic() - t0)
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read())
            except Exception:
                payload = None
            out = (e.code, payload, time.monotonic() - t0)
        except Exception as e:
            out = (-1, repr(e), time.monotonic() - t0)
        with lock:
            results.append(out)

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(24)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in threads), "hung clients"

    assert len(results) == 24
    ok = [r for r in results if r[0] == 200]
    shed = [r for r in results if r[0] in (429, 503)]
    other = [r for r in results if r[0] not in (200, 429, 503)]
    assert not other, f"bare failures: {other}"
    assert ok, "storm starved every client"
    for status, payload, elapsed in shed:
        assert isinstance(payload, dict) and "error" in payload, (
            f"shed response not typed JSON: {payload}")
        # queue_deadline_s=1.5 plus scheduling slack: shed, never stalled
        assert elapsed < 10.0, f"shed took {elapsed:.1f}s"
    serve.shutdown()


