"""Ops plane: job submission, autoscaler, dashboard.

Parity targets: reference python/ray/tests/test_job_manager.py (submit /
status / logs / stop), autoscaler v2 tests
(python/ray/autoscaler/v2/tests/test_autoscaler.py via the fake provider),
and dashboard/tests (HTTP endpoints return live state).
"""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.job_submission import JobStatus, JobSubmissionClient


def _wait(pred, timeout=60.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.1)
    raise TimeoutError(f"timed out waiting for {what}")


@pytest.fixture
def job_client(ray_start_2cpu):
    client = JobSubmissionClient()
    yield client
    client.close()


def test_job_submit_success_and_logs(job_client):
    script = (
        "import ray_tpu; ray_tpu.init();"
        "f = ray_tpu.remote(lambda x=2: x * 21);"
        "print('answer:', ray_tpu.get(f.remote(), timeout=60));"
        "ray_tpu.shutdown()"
    )
    sid = job_client.submit_job(entrypoint=f'python -c "{script}"')
    status = job_client.wait_until_finished(sid, timeout=120)
    logs = job_client.get_job_logs(sid)
    assert status == JobStatus.SUCCEEDED, logs
    assert "answer: 42" in logs
    jobs = job_client.list_jobs()
    assert any(j["submission_id"] == sid for j in jobs)


def test_job_failure_reports_exit_code(job_client):
    sid = job_client.submit_job(entrypoint="python -c 'raise SystemExit(3)'")
    status = job_client.wait_until_finished(sid, timeout=60)
    assert status == JobStatus.FAILED
    info = job_client.get_job_info(sid)
    assert "exited with code 3" in info["message"]


def test_job_stop(job_client):
    sid = job_client.submit_job(entrypoint="python -c 'import time; time.sleep(600)'")
    _wait(lambda: job_client.get_job_status(sid) == JobStatus.RUNNING,
          what="job running")
    assert job_client.stop_job(sid)
    _wait(lambda: job_client.get_job_status(sid) == JobStatus.STOPPED,
          what="job stopped")


def test_autoscaler_scales_up_and_down(shutdown_only):
    from ray_tpu.autoscaler import Autoscaler, LocalNodeProvider
    from ray_tpu._private.worker import global_worker

    ray_tpu.init(num_cpus=1)
    w = global_worker()
    address = f"{w.controller_addr[0]}:{w.controller_addr[1]}"
    provider = LocalNodeProvider(address, w.session_id, node_shape={"CPU": 2})
    scaler = Autoscaler(address, provider, min_workers=0, max_workers=2,
                        idle_timeout_s=3.0, interval_s=0.5)
    scaler.start()
    try:
        # Head has 1 CPU; this actor needs 2 -> pure demand for the scaler.
        @ray_tpu.remote
        class Big:
            def where(self):
                import os
                return os.environ.get("RT_NODE_ID")

        a = Big.options(num_cpus=2).remote()
        node = ray_tpu.get(a.where.remote(), timeout=120)
        assert node is not None
        assert len(provider.non_terminated_nodes()) >= 1
        # Free the resources: the idle node must be reaped.
        ray_tpu.kill(a)
        _wait(lambda: len(provider.non_terminated_nodes()) == 0, timeout=60,
              what="idle scale-down")
    finally:
        scaler.stop()


def test_dashboard_endpoints(ray_start_2cpu):
    from ray_tpu.dashboard import start_dashboard

    @ray_tpu.remote
    def touch():
        return 1

    assert ray_tpu.get(touch.remote(), timeout=60) == 1
    d = start_dashboard(port=0)
    try:
        base = f"http://127.0.0.1:{d.port}"

        def get(path):
            with urllib.request.urlopen(base + path, timeout=10) as r:
                return json.loads(r.read())

        status = get("/api/cluster_status")
        assert "total" in status and status["total"].get("CPU", 0) >= 2
        nodes = get("/api/nodes")["nodes"]
        assert any(n["alive"] for n in nodes)
        tasks = get("/api/tasks")["tasks"]
        assert any(t["name"] == "touch" for t in tasks)
        assert get("/api/jobs")["jobs"] == []
        trace = get("/api/timeline")
        assert any(ev.get("name") == "touch" for ev in trace)
    finally:
        d.stop()


def test_remote_driver_client(ray_start_cluster):
    """util.client: the remote-driver mode (reference ray://) — the full
    API from a process holding only a controller address."""
    from ray_tpu.util.client import connect

    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    ctx = connect(f"ray://{cluster.address}")
    try:
        @ray_tpu.remote
        def f(x):
            return x + 1

        assert ray_tpu.get(f.remote(41), timeout=60) == 42
        assert "connected" in repr(ctx)
    finally:
        ctx.disconnect()
    assert not ray_tpu.is_initialized()


def test_dashboard_index_ui(ray_start_2cpu):
    """The dashboard serves the live HTML view (reference React client's
    role) alongside the JSON APIs."""
    import urllib.request

    from ray_tpu.dashboard import Dashboard

    w = ray_tpu._private.worker.global_worker()
    dash = Dashboard(f"{w.controller_addr[0]}:{w.controller_addr[1]}",
                     port=0)
    port = dash.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=10) as r:
            html = r.read().decode()
        assert "ray_tpu dashboard" in html
        assert "/api/cluster_status" in html  # the UI polls the APIs
        assert "<script>" in html
    finally:
        dash.stop()
