"""Object-plane fault tolerance: lineage reconstruction, spill/restore,
chaos under a mixed workload.

reference tests: python/ray/tests/test_reconstruction.py,
test_object_spilling.py, and the ResourceKillerActor chaos pattern
(python/ray/_private/test_utils.py:1386).
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy


def test_lineage_reconstruction_node_death(ray_start_cluster):
    """Kill the only node holding a non-inline result: get() must re-run
    the producing task elsewhere (reference test_reconstruction.py)."""
    cluster = ray_start_cluster
    n2 = cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address)

    @ray_tpu.remote(max_retries=2, scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=n2.node_id, soft=True))
    def produce():
        import os

        # 2MB: far beyond the inline threshold -> lives in node shm
        return {"node": os.environ.get("RT_NODE_ID"),
                "data": np.full(1 << 19, 7, dtype=np.float32)}

    ref = produce.remote()
    done, _ = ray_tpu.wait([ref], num_returns=1, timeout=120)
    assert done, "produce() never finished"
    # Do NOT get() first: the driver must not hold a local copy. Prove the
    # only copy actually lives on n2 (soft affinity could in principle
    # place elsewhere, which would silently skip the reconstruction path).
    from ray_tpu.util import state

    time.sleep(0.3)  # let the holder advertise land
    ent = next(o for o in state.list_objects(limit=10_000)
               if o["object_id"] == ref.hex())
    n2_addr = next(tuple(n["address"]) for n in state.list_nodes()
                   if n["node_id"] == n2.node_id)
    assert any(tuple(h) == n2_addr for h in ent["holders"]), (ent, n2_addr)
    cluster.remove_node(n2)
    out = ray_tpu.get(ref, timeout=120)
    assert float(out["data"].sum()) == 7.0 * (1 << 19)


def test_spill_and_restore_over_capacity(shutdown_only, tmp_path):
    """Puts beyond object_store_memory_bytes spill to disk and read back
    intact (reference test_object_spilling.py)."""
    ray_tpu.init(num_cpus=2, _system_config={
        "object_store_memory_bytes": 4 * 1024 * 1024,
        "object_spill_dir": str(tmp_path / "spill"),
    })
    arrays = [np.full(1 << 18, i, dtype=np.float32) for i in range(10)]  # 10MB total
    refs = [ray_tpu.put(a) for a in arrays]
    for i, r in enumerate(refs):  # oldest were spilled; all must restore
        got = ray_tpu.get(r, timeout=60)
        assert float(got[0]) == float(i)
        assert got.shape == (1 << 18,)


def test_chaos_mixed_workload(ray_start_cluster):
    """NodeKiller cycles nodes while retried tasks + an actor keep working;
    the workload completes correctly despite the churn."""
    from ray_tpu.util.chaos import NodeKiller

    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address)

    @ray_tpu.remote(max_retries=16)  # chaos can kill the same task repeatedly
    def flaky_sum(i):
        time.sleep(0.25)
        return i * 2

    @ray_tpu.remote(max_restarts=8, max_task_retries=8,
                    scheduling_strategy=NodeAffinitySchedulingStrategy(
                        node_id=cluster.head.node_id, soft=False))
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    counter = Counter.remote()
    killer = NodeKiller(cluster, interval_s=0.5, max_kills=2,
                        node_resources={"num_cpus": 2}).start()
    try:
        refs = [flaky_sum.remote(i) for i in range(40)]
        out = ray_tpu.get(refs, timeout=240)
        assert out == [i * 2 for i in range(40)]
        assert ray_tpu.get(counter.bump.remote(), timeout=60) == 1
    finally:
        killer.stop()
    assert killer.kills >= 1, "chaos killer never fired"
