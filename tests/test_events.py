"""Cluster event plane (README "Cluster events"): lifecycle events with
monotonic seqs, a per-entity index, storage-backed JSONL segments, the
normalized worker-exit cause enum, error-message enrichment, and the
job-logs truncation contract that rides along in the same PR."""

import json
import os
import signal
import time

import pytest

import ray_tpu
from ray_tpu._private import events as events_mod
from ray_tpu.util import state


def _wait_for(pred, timeout=20.0, interval=0.2, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = pred()
        if out:
            return out
        time.sleep(interval)
    raise TimeoutError(f"timed out waiting for {what}")


def test_lifecycle_events_seq_ordered_and_entity_indexed(ray_start_2cpu):
    @ray_tpu.remote
    class A:
        def ping(self):
            return "pong"

    a = A.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "pong"

    rows = _wait_for(
        lambda: [e for e in state.list_events()
                 if e["kind"] in ("actor_create", "actor_ready")] or None,
        what="actor lifecycle events")
    kinds = [e["kind"] for e in rows]
    assert "actor_create" in kinds and "actor_ready" in kinds
    # seqs are strictly increasing in list order (arrival-order minting).
    all_rows = state.list_events()
    seqs = [e["seq"] for e in all_rows]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    # Every emitted kind is declared (the registry the rtcheck event-kinds
    # pass enforces statically holds at runtime too).
    for e in all_rows:
        assert e["kind"] in events_mod.KINDS, e
        assert e["sev"] in events_mod.SEVERITIES, e
    # Entity filter: the actor's id prefix-matches only its own chain.
    mine = state.list_events(entity=a._actor_id[:12])
    assert mine and all(
        any(str(x).startswith(a._actor_id[:12]) for x in e["entity"])
        for e in mine)
    assert [e["kind"] for e in mine][:2] == ["actor_create", "actor_ready"]
    # Kind + severity filters.
    assert all(e["kind"] == "actor_ready"
               for e in state.list_events(kind="actor_ready"))
    assert all(e["sev"] == "debug"
               for e in state.list_events(severity="debug"))
    # Worker spawns arrive via the heartbeat piggyback path.
    _wait_for(lambda: state.list_events(kind="worker_start") or None,
              what="worker_start via heartbeat")
    # since= is an exclusive seq cursor (the --follow contract).
    last = all_rows[-1]["seq"]
    assert all(e["seq"] > last for e in state.list_events(since=last))


def test_worker_exit_cause_normalized_and_error_enriched(ray_start_2cpu):
    @ray_tpu.remote(max_restarts=0)
    class Frail:
        def pid(self):
            return os.getpid()

    f = Frail.remote()
    pid = ray_tpu.get(f.pid.remote(), timeout=60)
    os.kill(pid, signal.SIGKILL)
    ev = _wait_for(
        lambda: next((e for e in state.list_events(kind="worker_exit")
                      if (e.get("attrs") or {}).get("pid") == pid), None),
        what="worker_exit event")
    # The normalized cause enum — not a raw signal int, not "killed".
    assert (ev["attrs"]["cause"] == events_mod.CAUSE_CRASH
            and ev["attrs"]["cause"] in events_mod.EXIT_CAUSES)
    # Error enrichment: the ActorDiedError a caller sees names the event
    # seq range that explains the death.
    def _dead_error():
        try:
            ray_tpu.get(f.pid.remote(), timeout=10)
            return None
        except ray_tpu.exceptions.ActorDiedError as e:
            return str(e)

    msg = _wait_for(_dead_error, what="ActorDiedError")
    assert "[events " in msg and "ray-tpu events --entity" in msg, msg
    death = _wait_for(
        lambda: state.list_events(entity=f._actor_id, kind="actor_death")
        or None, what="actor_death event")
    assert death[-1]["sev"] == "error"

    # Explicit kills are a DIFFERENT cause: ray_tpu.kill routes through
    # the agent's kill_worker path, which has no worker_died report — the
    # event must still appear, with cause "killed" (not crash).
    @ray_tpu.remote
    class Victim:
        def pid(self):
            return os.getpid()

    v = Victim.remote()
    vpid = ray_tpu.get(v.pid.remote(), timeout=60)
    ray_tpu.kill(v)
    kev = _wait_for(
        lambda: next((e for e in state.list_events(kind="worker_exit")
                      if (e.get("attrs") or {}).get("pid") == vpid), None),
        what="killed worker_exit event")
    assert kev["attrs"]["cause"] == events_mod.CAUSE_KILLED, kev
    # Exactly one exit event per worker (the slot-level dedup).
    exits = [e for e in state.list_events(kind="worker_exit")
             if (e.get("attrs") or {}).get("pid") == vpid]
    assert len(exits) == 1, exits


def test_events_plane_off_is_inert(shutdown_only, monkeypatch):
    monkeypatch.setenv("RT_EVENTS_BUFFER", "0")
    events_mod.refresh()
    try:
        ray_tpu.init(num_cpus=1)

        @ray_tpu.remote
        class A:
            def ping(self):
                return 1

        a = A.remote()
        assert ray_tpu.get(a.ping.remote(), timeout=60) == 1
        time.sleep(1.2)
        rows = state.list_events()
        assert rows == [] and not rows.truncated
        # Agent side: no pending deque at all — heartbeat frames carry no
        # `events` key (byte-identical to a plane-free build).
        assert ray_tpu._head.agent._pending_events is None
        assert ray_tpu._head.controller.events.maxlen is None \
            and len(ray_tpu._head.controller.events) == 0
        # Driver-side emission is a no-op, not a buffered leak.
        events_mod.emit_event("job_start", "should vanish")
        assert events_mod.drain() == []
    finally:
        monkeypatch.delenv("RT_EVENTS_BUFFER", raising=False)
        events_mod.refresh()


def test_event_persistence_segments_and_rotation(tmp_path, shutdown_only,
                                                 monkeypatch):
    ev_dir = str(tmp_path / "ev")
    monkeypatch.setenv("RT_EVENTS_DIR", ev_dir)
    monkeypatch.setenv("RT_EVENTS_SEGMENT_EVENTS", "16")
    monkeypatch.setenv("RT_EVENTS_KEEP_SEGMENTS", "3")
    ray_tpu.init(num_cpus=1)
    head = ray_tpu._head
    ctrl = head.controller

    async def _pump(n):
        ctrl._ingest_events([
            events_mod.build_event("job_start", f"synthetic {i}",
                                   entity=(f"job{i % 7}",))
            for i in range(n)])

    head.io.run(_pump(100))

    def _segments():
        try:
            return sorted(n for n in os.listdir(ev_dir)
                          if n.startswith("seg-") and n.endswith(".jsonl"))
        except OSError:
            return []

    segs = _wait_for(
        lambda: s if len(s := _segments()) and len(s) <= 3 else None,
        what="rotated segments")
    # keep-last-K rotation: 100 events / 16 per segment > 3 kept.
    assert 1 <= len(segs) <= 3
    # Segments are parseable JSONL with strictly increasing seqs, and the
    # file name carries the segment's LAST seq (the restore-scan contract).
    last_seen = -1
    for name in segs:
        with open(os.path.join(ev_dir, name)) as fh:
            rows = [json.loads(ln) for ln in fh if ln.strip()]
        assert rows and all(r["kind"] == "job_start" for r in rows)
        seqs = [r["seq"] for r in rows]
        assert seqs == sorted(seqs) and seqs[0] > last_seen
        last_seen = seqs[-1]
        assert int(name[len("seg-"):-len(".jsonl")]) == seqs[-1]
    # The in-progress tail rewrites as current.jsonl.
    _wait_for(lambda: os.path.exists(os.path.join(ev_dir, "current.jsonl")),
              what="current.jsonl tail")
    # Driver-side emit_event rides the metrics flush into the same ring.
    events_mod.emit_event("job_stop", "driver emitted",
                          entity=("driver-ev",))
    rows = _wait_for(lambda: state.list_events(entity="driver-ev") or None,
                     what="driver event via metrics flush")
    assert rows[-1]["kind"] == "job_stop"


def test_snapshot_restore_seq_never_collides(tmp_path, monkeypatch):
    """Satellite: a restored head must not re-mint seqs that collide with
    persisted segments — via the snapshot watermark AND the segment scan
    (which covers seqs minted after the last snapshot)."""
    from ray_tpu._private.controller import Controller

    ev_dir = str(tmp_path / "ev")
    monkeypatch.setenv("RT_EVENTS_DIR", ev_dir)
    c1 = Controller("sess-events")
    c1._ingest_events([events_mod.build_event("job_start", f"e{i}",
                                              entity=(f"j{i}",))
                       for i in range(10)])
    assert c1._event_seq == 10
    snap = c1._build_snapshot()
    assert snap["events_seq"] == 10
    # Persist everything the sweep would have (5 full + tail of 5 under a
    # synthetic segment size), using the same sync helper the sweep uses.
    buf = list(c1._evseg_buf)
    c1._persist_event_segments_sync(ev_dir, [buf[:5]], buf[5:], 4, 0)
    # Restore path 1: segment scan alone (snapshot lost/stale at 0).
    c2 = Controller("sess-events")
    assert c2._event_seq == 0
    c2._restore_event_seq()
    assert c2._event_seq == 10, (
        f"restored head would re-mint seq {c2._event_seq} colliding with "
        f"persisted history")
    # History survives the restart QUERYABLY: the ring and entity index
    # reload from the persisted segments + current tail.
    assert [e["seq"] for e in c2.events] == list(range(10))
    assert c2._event_index  # entity index rebuilt
    # current.jsonl's tail events refill the persistence buffer (they live
    # in no full segment yet — the next tail rewrite must keep them).
    assert [e["seq"] for e in c2._evseg_buf] == list(range(5, 10))
    c2._ingest_events([events_mod.build_event("job_start", "fresh")])
    assert c2.events[-1]["seq"] == 10
    # Restore path 2: the snapshot watermark beats an even staler scan.
    c3 = Controller("sess-events")
    c3._event_seq = int(snap["events_seq"])
    c3._restore_event_seq()
    assert c3._event_seq >= 10
    # Crash window: killed between the seg-N write and the current.jsonl
    # rewrite, the tail exists in BOTH files. Restore dedupes by seq and
    # only segment-uncovered tail events refill the persistence buffer —
    # the duplicate never becomes permanent in durable history.
    ev_dir2 = str(tmp_path / "ev2")
    monkeypatch.setenv("RT_EVENTS_DIR", ev_dir2)
    c1._persist_event_segments_sync(ev_dir2, [buf[:8]], buf[5:], 4, 0)
    c4 = Controller("sess-events")
    c4._restore_event_seq()
    assert [e["seq"] for e in c4.events] == list(range(10))  # deduped
    assert [e["seq"] for e in c4._evseg_buf] == [8, 9]  # covered tail out
    assert c4._event_seq == 10


def test_job_logs_capped_with_truncated_marker(ray_start_2cpu, monkeypatch):
    """Satellite: one job_logs RPC returns at most JOB_LOG_CHUNK_BYTES and
    marks clipped replies truncated; the client loops to EOF."""
    from ray_tpu._private.node_agent import NodeAgent
    from ray_tpu.job_submission import JobSubmissionClient

    monkeypatch.setattr(NodeAgent, "JOB_LOG_CHUNK_BYTES", 512)
    w = ray_tpu._private.worker.global_worker()
    client = JobSubmissionClient(
        f"{w.controller_addr[0]}:{w.controller_addr[1]}")
    try:
        sid = client.submit_job(
            entrypoint="python -c \"print('x' * 5000)\"")
        assert client.wait_until_finished(sid, timeout=120) == "SUCCEEDED"
        # Direct agent contract: capped reply, truncated marker set.
        rep = ray_tpu._head.agent._job_logs(sid, 0)
        assert rep["found"] and len(rep["data"]) == 512 and rep["truncated"]
        # EOF reply: not truncated.
        end = ray_tpu._head.agent._job_logs(sid, 1 << 30)
        assert end["found"] and end["data"] == b"" and not end["truncated"]
        # The client loops on the marker and reassembles the whole log.
        logs = client.get_job_logs(sid)
        assert "x" * 5000 in logs
    finally:
        client.close()


def test_dashboard_api_events(ray_start_2cpu):
    import urllib.request

    @ray_tpu.remote
    class A:
        def ping(self):
            return 1

    a = A.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == 1
    _wait_for(lambda: state.list_events(kind="actor_ready") or None,
              what="actor_ready event")
    from ray_tpu.dashboard import start_dashboard

    d = start_dashboard(port=0)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{d.port}/api/events", timeout=10) as r:
            rep = json.loads(r.read())
        kinds = {e["kind"] for e in rep["events"]}
        assert {"actor_create", "actor_ready"} <= kinds, kinds
        assert isinstance(rep["next_seq"], int)
        ent = a._actor_id[:12]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{d.port}/api/events?entity={ent}"
                f"&kind=actor_ready", timeout=10) as r:
            rep = json.loads(r.read())
        assert rep["events"] and all(
            e["kind"] == "actor_ready" for e in rep["events"])
    finally:
        d.stop()


def test_cli_events_command(ray_start_2cpu, capsys):
    from ray_tpu.scripts import cli

    @ray_tpu.remote
    class A:
        def ping(self):
            return 1

    a = A.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == 1
    _wait_for(lambda: state.list_events(kind="actor_ready") or None,
              what="actor_ready event")
    w = ray_tpu._private.worker.global_worker()
    addr = f"{w.controller_addr[0]}:{w.controller_addr[1]}"
    assert cli.main(["events", "--address", addr]) == 0
    out = capsys.readouterr().out
    assert "actor_ready" in out and "SEQ" in out
    assert cli.main(["events", "--address", addr, "--entity",
                     a._actor_id[:12]]) == 0
    out = capsys.readouterr().out
    assert "actor_create" in out and "node_register" not in out
