"""Mesh, sharding, and device-collective tests on the virtual 8-device CPU
mesh (the load-bearing multi-chip test mechanism, SURVEY §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.parallel import MeshConfig, build_mesh, local_mesh
from ray_tpu.parallel import collectives as col

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices")


def test_mesh_config_resolve():
    assert MeshConfig(dp=-1).resolve(8)["dp"] == 8
    sizes = MeshConfig(dp=2, tp=2, sp=2).resolve(8)
    assert sizes == {"dp": 2, "fsdp": 1, "pp": 1, "sp": 2, "tp": 2, "ep": 1}
    with pytest.raises(ValueError):
        MeshConfig(dp=3).resolve(8)


def test_build_mesh_axes():
    mesh = build_mesh(MeshConfig(dp=2, tp=4))
    assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 4
    assert mesh.devices.size == 8


def test_psum_shard_map():
    mesh = local_mesh(8, axis="dp")
    x = np.arange(8, dtype=np.float32)
    out = col.mesh_allreduce(mesh, x, axis_name="dp")
    np.testing.assert_allclose(np.asarray(out), np.full(1, x.sum()))


def test_all_gather_and_ppermute():
    mesh = local_mesh(8, axis="sp")

    def body(x):
        g = col.all_gather(x, "sp", axis=0)
        r = col.ppermute_ring(x, "sp", mesh, shift=1)
        return g, r

    fn = col.shard_map(body, mesh=mesh, in_specs=P("sp"), out_specs=(P(), P("sp")))
    x = np.arange(8, dtype=np.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P("sp")))
    gathered, rotated = jax.jit(fn)(xs)
    np.testing.assert_allclose(np.asarray(gathered), x)
    # shift=1 sends shard i to position i+1: rotated[i] = x[i-1]
    np.testing.assert_allclose(np.asarray(rotated), np.roll(x, 1))


def test_all_to_all():
    mesh = local_mesh(8, axis="ep")

    def body(x):  # x local: [1, 8] -> transpose-ish exchange
        return col.all_to_all(x, "ep", split_axis=1, concat_axis=0)

    # Tiled all_to_all is a global identity that RESHARDS: row-sharded in,
    # column-sharded out (the Ulysses sequence<->head redistribution
    # primitive). Each device i ends up holding column i.
    fn = col.shard_map(body, mesh=mesh, in_specs=P("ep", None), out_specs=P(None, "ep"))
    x = np.arange(64, dtype=np.float32).reshape(8, 8)
    xs = jax.device_put(x, NamedSharding(mesh, P("ep", None)))
    out = jax.jit(fn)(xs)
    np.testing.assert_allclose(np.asarray(out), x)
    assert out.sharding.spec == P(None, "ep")


def test_transformer_sharded_matches_single_device():
    import optax

    from ray_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                                n_kv_heads=4, d_ff=172, max_seq=32, dtype=jnp.float32)
    model = tfm.Transformer(cfg)
    rng = jax.random.PRNGKey(1)
    tokens = jax.random.randint(rng, (4, 17), 0, cfg.vocab_size, dtype=jnp.int32)
    params = model.init(rng, tokens[:, :-1])

    ref_loss = float(tfm.loss_fn(model, params, tokens))

    mesh = build_mesh(MeshConfig(dp=2, sp=2, tp=2))
    pspecs = tfm.param_specs(params)
    shardings = jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), pspecs)
    params_s = jax.tree_util.tree_map(jax.device_put, params, shardings)
    tokens_s = jax.device_put(tokens, NamedSharding(mesh, P(("dp", "fsdp"), None)))
    with mesh:
        loss = float(jax.jit(lambda p, t: tfm.loss_fn(model, p, t))(params_s, tokens_s))
    assert abs(loss - ref_loss) < 1e-4


def test_gqa_attention_matches_mha_expansion():
    from ray_tpu.ops import dot_product_attention

    rng = jax.random.PRNGKey(0)
    q = jax.random.normal(rng, (2, 16, 8, 16))
    k = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 2, 16))
    v = jax.random.normal(jax.random.PRNGKey(2), (2, 16, 2, 16))
    out_gqa = dot_product_attention(q, k, v, causal=True, use_pallas=False)
    k_full = jnp.repeat(k, 4, axis=2)
    v_full = jnp.repeat(v, 4, axis=2)
    out_full = dot_product_attention(q, k_full, v_full, causal=True, use_pallas=False)
    np.testing.assert_allclose(np.asarray(out_gqa), np.asarray(out_full), atol=1e-5)


def test_dryrun_multichip_entrypoint():
    import subprocess
    import sys

    r = subprocess.run(
        [sys.executable, "-c",
         "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)"],
        capture_output=True, text=True, timeout=300,
        cwd=__import__("os").path.dirname(__import__("os").path.dirname(__file__)))
    assert r.returncode == 0, r.stderr[-2000:]
    assert "OK" in r.stdout


def test_pipeline_matches_sequential():
    """GPipe over pp=2: loss AND grads through the microbatched ring must
    equal the single-device sequential apply (backward pipeline via the
    autodiff transpose of ppermute)."""
    from jax.sharding import Mesh

    from ray_tpu.parallel.pipeline import (
        PipelineConfig, init_params, pipeline_loss_fn, reference_loss)

    cfg = PipelineConfig(vocab_size=128, d_model=64, n_layers=4, n_heads=4,
                         d_ff=128, n_microbatches=4)
    params = init_params(cfg)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (8, 17)), jnp.int32)
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("pp",))
    loss_fn = pipeline_loss_fn(cfg, mesh)
    with mesh:
        loss, grads = jax.jit(jax.value_and_grad(loss_fn))(params, tokens)
    ref_loss = float(reference_loss(cfg, params, tokens))
    assert abs(float(loss) - ref_loss) < 1e-5
    ref_grads = jax.jit(jax.grad(
        lambda p, t: reference_loss(cfg, p, t)))(params, tokens)
    for a, b in zip(jax.tree_util.tree_leaves(grads),
                    jax.tree_util.tree_leaves(ref_grads)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_moe_ep_sharding_matches_single_device():
    """Top-2 MoE with experts sharded over ep=2: loss equals the unsharded
    forward (dense dispatch is deterministic)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.models import transformer as tfm
    from ray_tpu.parallel.mesh import MeshConfig, build_mesh

    cfg = tfm.TransformerConfig(vocab_size=128, d_model=64, n_layers=2,
                                n_heads=4, n_kv_heads=4, d_ff=96, max_seq=32,
                                dtype=jnp.float32, moe_experts=4)
    model = tfm.Transformer(cfg)
    tokens = jnp.asarray(
        np.random.RandomState(1).randint(0, cfg.vocab_size, (4, 17)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens[:, :-1])
    ref = float(tfm.loss_fn(model, params, tokens))

    mesh = build_mesh(MeshConfig(dp=2, fsdp=2, ep=2), devices=jax.devices()[:8])
    shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), tfm.param_specs(params))
    params_s = jax.tree_util.tree_map(jax.device_put, params, shardings)
    tokens_s = jax.device_put(tokens, NamedSharding(mesh, P(("dp", "fsdp"), None)))
    with mesh:
        loss = float(jax.jit(
            lambda p, t: tfm.loss_fn(model, p, t))(params_s, tokens_s))
    assert abs(loss - ref) < 1e-4
