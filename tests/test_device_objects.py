"""Device object plane: actor-resident `jax.Array` ObjectRefs with tiered
resolution (README "Device objects"; reference: the direct-transport
GPU-object design — device values stay pinned in the producer and move
peer-to-peer instead of round-tripping through the object store).

Runs on the tier-1 CPU backend (conftest `device_plane_cpu` guard): cpu
jax.Arrays exercise the exact same DeviceObjectTable / placeholder /
refcount / free-fan-out paths as TPU-resident arrays.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.experimental import device_objects

# 128KB float32 — comfortably above RT_DEVICE_OBJECT_MIN_BYTES (100KB).
N = 1 << 15


def _plane_of(oid: str, deadline_s: float = 10.0):
    """Poll the state API for an object's plane field (advertises are
    batched one-way pushes, so the directory entry can trail the ref)."""
    from ray_tpu.util import state

    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        for o in state.list_objects(limit=100_000):
            if o["object_id"] == oid:
                return o["plane"]
        time.sleep(0.1)
    return None


def test_same_process_get_zero_copy(ray_start_2cpu, device_plane_cpu):
    """Acceptance pin: a same-process get() of a device object performs
    ZERO host copies — it returns the live pinned array itself."""
    jnp = device_plane_cpu.numpy
    arr = jnp.arange(N, dtype=jnp.float32)
    ref = ray_tpu.put(arr)
    got = ray_tpu.get(ref)
    assert got is arr  # identity, not a reconstruction
    assert got.unsafe_buffer_pointer() == arr.unsafe_buffer_pointer()
    # Repeat gets stay zero-copy.
    assert ray_tpu.get(ref) is arr
    assert device_objects.device_object_stats()["count"] >= 1


def test_actor_return_rides_device_plane(ray_start_2cpu, device_plane_cpu):
    @ray_tpu.remote(num_cpus=0)
    class Producer:
        def make(self, i):
            import jax.numpy as jnp

            return jnp.full((N,), float(i), jnp.float32)

        def stats(self):
            from ray_tpu.experimental import device_objects as dob

            return dob.device_object_stats()

    p = Producer.remote()
    ref = p.make.remote(7)
    got = ray_tpu.get(ref, timeout=60)
    # Cross-process tier: a real jax.Array with the right contents.
    assert isinstance(got, device_plane_cpu.Array)
    assert np.asarray(got).dtype == np.float32
    assert float(np.asarray(got).sum()) == 7.0 * N
    # The payload stayed pinned producer-side...
    stats = ray_tpu.get(p.stats.remote(), timeout=60)
    assert stats["count"] >= 1 and stats["bytes"] >= 4 * N
    # ...and the directory entry is marked device-plane.
    assert _plane_of(ref.hex()) == "device"


def test_arg_handoff_and_second_consumer(ray_start_4cpu, device_plane_cpu):
    """Producer -> consumer handoff through a ref arg, plus a SECOND
    consumer of the same ref: both resolve to jax.Arrays with the same
    contents (the second attaches the existing export — type and value
    must not depend on which tier served the read)."""

    @ray_tpu.remote(num_cpus=0)
    class Producer:
        def make(self):
            import jax.numpy as jnp

            return jnp.arange(N, dtype=jnp.float32)

    @ray_tpu.remote(num_cpus=0)
    class Consumer:
        def consume(self, a):
            import jax

            assert isinstance(a, jax.Array), type(a)
            return float(np.asarray(a).sum())

    p = Producer.remote()
    c1, c2 = Consumer.remote(), Consumer.remote()
    ref = p.make.remote()
    expect = float(np.arange(N, dtype=np.float32).sum())
    assert ray_tpu.get(c1.consume.remote(ref), timeout=60) == expect
    assert ray_tpu.get(c2.consume.remote(ref), timeout=60) == expect


def test_task_return_device_plane(ray_start_2cpu, device_plane_cpu):
    """Plain (leased-path) task returns ride the plane too."""

    @ray_tpu.remote
    def mk():
        import jax.numpy as jnp

        return jnp.ones((N,), jnp.float32)

    got = ray_tpu.get(mk.remote(), timeout=60)
    assert isinstance(got, device_plane_cpu.Array)
    assert float(np.asarray(got).sum()) == float(N)


def test_device_arg_inlines_placeholder(ray_start_2cpu, device_plane_cpu):
    """A large jax.Array ARGUMENT is promoted to a device ref whose
    placeholder rides inside the spec (task_spec.DEVICE_REF) — the
    executor resolves it peer-to-peer from the driver's table."""
    jnp = device_plane_cpu.numpy

    @ray_tpu.remote
    def total(a):
        import jax

        assert isinstance(a, jax.Array), type(a)
        return float(np.asarray(a).sum())

    big = jnp.full((N,), 2.0, jnp.float32)
    ref = total.remote(big)
    assert ray_tpu.get(ref, timeout=60) == 2.0 * N
    # The driver's table holds the pinned arg while the result ref lives...
    assert device_objects.device_object_stats()["count"] >= 1
    # ...and releases it when the result ref dies (a fresh-array-per-call
    # loop must not accrete one pinned arg per iteration).
    del ref
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if device_objects.device_object_stats()["count"] == 0:
            break
        time.sleep(0.1)
    assert device_objects.device_object_stats()["count"] == 0


def test_fire_and_forget_device_arg(ray_start_2cpu, device_plane_cpu):
    """The ubiquitous fire-and-forget pattern — submit with a big array
    arg, drop the result ref immediately — must not free the pinned arg
    before the executor decodes it (the until-task-done hold)."""
    jnp = device_plane_cpu.numpy

    @ray_tpu.remote(num_cpus=0)
    class Sink:
        def __init__(self):
            self.total = 0.0

        def update(self, a):
            self.total += float(np.asarray(a).sum())

        def read(self):
            return self.total

    s = Sink.remote()
    for i in range(5):
        s.update.remote(jnp.full((N,), float(i), jnp.float32))  # ref dropped
    assert ray_tpu.get(s.read.remote(), timeout=60) == sum(
        float(i) * N for i in range(5))
    # ...and once the calls completed, the dropped refs release the pins.
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if device_objects.device_object_stats()["count"] == 0:
            break
        time.sleep(0.1)
    assert device_objects.device_object_stats()["count"] == 0


def test_plane_off_restores_host_path(shutdown_only, device_plane_cpu):
    """RT_DEVICE_OBJECTS=0 (here via _system_config) restores the host
    store path: values copy through shm/inline exactly as before — no
    pinning, no identity get, plane column reads "host"."""
    ray_tpu.init(num_cpus=2, _system_config={"device_objects": False})
    jnp = device_plane_cpu.numpy
    arr = jnp.arange(N, dtype=jnp.float32)
    ref = ray_tpu.put(arr)
    got = ray_tpu.get(ref)
    assert got is not arr  # host path reconstructs a copy
    assert np.array_equal(np.asarray(got), np.asarray(arr))
    assert device_objects.device_object_stats()["count"] == 0
    assert not device_objects.is_enabled()
    assert _plane_of(ref.hex()) == "host"

    @ray_tpu.remote(num_cpus=0)
    class Producer:
        def make(self):
            import jax.numpy as jnp

            return jnp.ones((N,), jnp.float32)

        def stats(self):
            from ray_tpu.experimental import device_objects as dob

            return dob.device_object_stats()

    p = Producer.remote()
    r = p.make.remote()
    assert float(np.asarray(ray_tpu.get(r, timeout=60)).sum()) == float(N)
    assert ray_tpu.get(p.stats.remote(), timeout=60)["count"] == 0
    assert _plane_of(r.hex()) == "host"


def test_small_and_sharded_arrays_fall_back(ray_start_2cpu, device_plane_cpu):
    """Sub-threshold arrays stay on the host/inline path; multi-device
    sharded arrays are not eligible (warn-once host fallback)."""
    jax, jnp = device_plane_cpu, device_plane_cpu.numpy
    small = jnp.arange(16, dtype=jnp.float32)
    assert not device_objects.would_ride_device_plane(small)
    ref = ray_tpu.put(small)
    assert ray_tpu.get(ref) is not small  # inline host path
    big = jnp.arange(N, dtype=jnp.float32)
    assert device_objects.would_ride_device_plane(big)
    if len(jax.devices()) > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = jax.sharding.Mesh(np.array(jax.devices()), ("d",))
        sharded = jax.device_put(big, NamedSharding(mesh, P("d")))
        assert not device_objects.would_ride_device_plane(sharded)
        # Round-trips intact through the host fallback.
        assert np.array_equal(
            np.asarray(ray_tpu.get(ray_tpu.put(sharded))), np.asarray(big))


def test_device_residency_gauges(ray_start_2cpu, device_plane_cpu):
    """The rt_device_objects_{count,bytes} gauges surface table residency
    through the metrics pipeline / state API."""
    from ray_tpu.util import state

    jnp = device_plane_cpu.numpy
    ref = ray_tpu.put(jnp.arange(N, dtype=jnp.float32))  # pins locally
    assert ref is not None
    deadline = time.monotonic() + 15
    seen = {}
    while time.monotonic() < deadline:
        seen = {m["name"]: m["value"] for m in state.metrics()
                if m["name"].startswith("rt_device_objects")}
        if seen.get("rt_device_objects_count", 0) >= 1:
            break
        time.sleep(0.25)
    assert seen.get("rt_device_objects_count", 0) >= 1, seen
    assert seen.get("rt_device_objects_bytes", 0) >= 4 * N, seen
