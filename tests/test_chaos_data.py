"""Chaos coverage for the streaming shuffle (README "Data plane"): a
SIGKILLed map or reduce worker mid-exchange re-executes through the task
retry + dedup plane and the output stays byte-identical (shards are
tagged by producing map index, merges order by tag); a severed sim://
spill backend surfaces an attributed DataSpillError after the bounded
retry budget — never a hang; a healthy spill path round-trips shards
bitwise through the storage plane."""

import os
import signal
import threading
import time

import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu.data._internal import exchange as xch
from ray_tpu.exceptions import DataSpillError


def _shuffle_blocks(items, seed, n_blocks):
    refs = rd.from_items(items, parallelism=n_blocks).random_shuffle(
        seed=seed)._block_refs()
    return [ray_tpu.get(r, timeout=600) for r in refs]


def _leased_pid():
    for slot in ray_tpu._head.agent.workers.values():
        if slot.state == "leased" and slot.proc.poll() is None:
            return slot.proc.pid
    return None


def _kill_leased_worker_when(pred, killed, timeout=30.0):
    """Background chaos: once `pred()` holds, SIGKILL a leased worker."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        pid = _leased_pid() if pred() else None
        if pid is not None:
            os.kill(pid, signal.SIGKILL)
            killed["pid"] = pid
            return
        time.sleep(0.002)


def test_sigkill_map_worker_mid_shuffle_output_identical(ray_start_2cpu,
                                                         monkeypatch):
    """Kill a worker while the map wave is live: retries re-execute the
    lost maps, tagged shards land in the same merge slots, and the
    shuffled blocks match the no-chaos run byte for byte."""
    monkeypatch.setenv("RT_DATA_MAX_INFLIGHT_BLOCKS", "4")
    items = [os.urandom(1024) for _ in range(768)]
    expect = _shuffle_blocks(items, seed=7, n_blocks=24)

    xch.reset_exchange_stats()
    killed = {"pid": None}
    t = threading.Thread(
        target=_kill_leased_worker_when,
        args=(lambda: 1 <= xch.exchange_stats()["maps_done"] < 20, killed))
    t.start()
    try:
        got = _shuffle_blocks(items, seed=7, n_blocks=24)
    finally:
        t.join(timeout=60)
    assert killed["pid"] is not None, "chaos kill never fired"
    assert got == expect, "shuffle output changed under a map-worker kill"


def test_sigkill_reduce_worker_mid_shuffle_output_identical(ray_start_2cpu,
                                                            monkeypatch):
    """Kill a worker once reduce-side consolidations are in flight (small
    fan-in makes them plentiful and early): the re-executed merges see the
    same tagged inputs and the output is byte-identical."""
    monkeypatch.setenv("RT_DATA_REDUCE_FANIN", "2")
    monkeypatch.setenv("RT_DATA_MAX_INFLIGHT_BLOCKS", "4")
    items = [os.urandom(1024) for _ in range(768)]
    expect = _shuffle_blocks(items, seed=8, n_blocks=24)

    xch.reset_exchange_stats()
    killed = {"pid": None}
    t = threading.Thread(
        target=_kill_leased_worker_when,
        args=(lambda: xch.exchange_stats()["reduces_submitted"] >= 4, killed))
    t.start()
    try:
        got = _shuffle_blocks(items, seed=8, n_blocks=24)
    finally:
        t.join(timeout=60)
    assert killed["pid"] is not None, "chaos kill never fired"
    assert got == expect, "shuffle output changed under a reduce-worker kill"


def test_severed_spill_backend_attributed_error_no_hang(shutdown_only,
                                                        monkeypatch,
                                                        tmp_path):
    """Every spill write hits a severed sim:// backend: the exchange fails
    within the bounded retry budget with a DataSpillError naming the shard
    uri and partition — it must never hang the consumer."""
    monkeypatch.setenv("RT_DATA_SPILL_URI", "sim://" + str(tmp_path / "sp"))
    monkeypatch.setenv("RT_DATA_MEM_CAP_BYTES", "1")  # every merge spills
    monkeypatch.setenv("RT_DATA_REDUCE_FANIN", "2")
    monkeypatch.setenv("RT_SIM_STORAGE_SEVERED", "1")  # workers inherit
    ray_tpu.init(num_cpus=2)
    items = [os.urandom(256) for _ in range(64)]
    t0 = time.monotonic()
    with pytest.raises(Exception) as ei:
        _shuffle_blocks(items, seed=4, n_blocks=8)
    elapsed = time.monotonic() - t0
    assert elapsed < 120, f"severed spill took {elapsed:.0f}s to surface"
    err = ei.value
    cause = getattr(err, "cause", None) or err.__cause__
    attributed = isinstance(err, DataSpillError) or \
        isinstance(cause, DataSpillError) or "DataSpillError" in str(err)
    assert attributed, f"unattributed failure: {err!r}"
    assert "sim://" in str(err) or (cause and "sim://" in str(cause)), (
        f"error does not name the spill uri: {err}")


def test_spill_restore_roundtrip_bitwise(shutdown_only, monkeypatch,
                                         tmp_path):
    """Healthy sim:// spill path: a mem-cap-forced spill through the sim
    backend restores bitwise — the spilled run's blocks equal a no-spill
    run's blocks exactly, and restores clean up their backing files."""
    items = [os.urandom(512) for _ in range(128)]
    ray_tpu.init(num_cpus=2)
    try:
        expect = _shuffle_blocks(items, seed=6, n_blocks=8)
    finally:
        ray_tpu.shutdown()

    fs_root = str(tmp_path / "sp")
    monkeypatch.setenv("RT_DATA_SPILL_URI", "sim://" + fs_root)
    monkeypatch.setenv("RT_DATA_MEM_CAP_BYTES", "1")  # every merge spills
    monkeypatch.setenv("RT_DATA_REDUCE_FANIN", "2")
    ray_tpu.init(num_cpus=2)
    got = _shuffle_blocks(items, seed=6, n_blocks=8)
    assert got == expect, "spill+restore changed the shuffle output"
    leftovers = [f for _r, _d, fs in os.walk(fs_root) for f in fs]
    assert leftovers == [], f"restored shards not cleaned up: {leftovers}"
