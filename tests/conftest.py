"""Shared fixtures.

Parity target: reference python/ray/tests/conftest.py (ray_start_regular:580,
shutdown_only:497, ray_start_cluster:668). Sharding tests run on a virtual
8-device CPU mesh (xla_force_host_platform_device_count), the load-bearing
mechanism for testing multi-chip SPMD without TPU hardware.
"""

import os

# Must be set before jax import anywhere in the test process tree. (The
# axon-tunnel escape hatch lives in _pytest_early_env.py, loaded via
# pytest.ini addopts before fd capture starts.)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    "--xla_force_host_platform_device_count=8 " + os.environ.get("XLA_FLAGS", "")
)
os.environ.setdefault("JAX_ENABLE_X64", "0")

import pytest  # noqa: E402

import ray_tpu  # noqa: E402


def pytest_collection_modifyitems(config, items):
    """`perf`-marked tests (bench smoke) run only on request (RT_RUN_PERF=1):
    they time things, so they are useless under tier-1's parallel load and
    would eat its time budget."""
    if os.environ.get("RT_RUN_PERF"):
        return
    skip = pytest.mark.skip(
        reason="perf smoke; set RT_RUN_PERF=1 to run (not part of tier-1)")
    for item in items:
        if "perf" in item.keywords:
            item.add_marker(skip)


@pytest.fixture
def device_plane_cpu():
    """Guard for device-object-plane tests under the tier-1 CPU backend:
    cpu jax.Arrays exercise the exact same DeviceObjectTable / placeholder /
    refcount / free-fan-out paths as TPU-resident ones (only the
    device_put target differs), so the plane is fully testable here. Skips
    cleanly if jax is unavailable, and asserts the plane wasn't disabled
    by ambient env (RT_DEVICE_OBJECTS) — these tests are about the plane."""
    jax = pytest.importorskip("jax")
    if os.environ.get("RT_DEVICE_OBJECTS", "").lower() in ("0", "false", "no"):
        pytest.skip("device object plane disabled via RT_DEVICE_OBJECTS")
    yield jax


@pytest.fixture
def shutdown_only():
    yield None
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_2cpu(shutdown_only):
    ray_tpu.init(num_cpus=2)
    yield


@pytest.fixture
def ray_start_4cpu(shutdown_only):
    ray_tpu.init(num_cpus=4)
    yield


@pytest.fixture
def ray_start_cluster():
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_node_args={"num_cpus": 1})
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()
