"""RLlib: env physics, GAE, PPO learning on CartPole, Tune integration.

reference tests: rllib/algorithms/ppo/tests/test_ppo.py,
rllib/env/tests/test_single_agent_env_runner.py; BASELINE.md names PPO
CartPole as a north-star workload.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import (
    CartPoleVecEnv,
    PPOConfig,
    compute_gae,
)


def test_cartpole_env_physics():
    env = CartPoleVecEnv(4, seed=0)
    obs = env.obs()
    assert obs.shape == (4, 4)
    assert np.abs(obs).max() <= 0.05
    # Constant-left policy must terminate within a few hundred steps.
    done_seen = np.zeros(4, dtype=bool)
    for _ in range(400):
        obs, rew, dones = env.step(np.zeros(4, dtype=np.int64))
        assert rew.shape == (4,) and np.all(rew == 1.0)
        done_seen |= dones.astype(bool)
    assert done_seen.all(), "constant policy never terminated"
    # auto-reset: post-done obs is back inside the init range
    assert np.abs(env.obs()).max() <= 2.4


def test_compute_gae_matches_manual():
    # T=3, N=1, no terminations: hand-derived GAE.
    gamma, lam = 0.9, 0.8
    rewards = np.array([[1.0], [1.0], [1.0]], np.float32)
    values = np.array([[0.5], [0.6], [0.7]], np.float32)
    dones = np.zeros((3, 1), np.float32)
    last_values = np.array([0.8], np.float32)
    adv, targets = compute_gae(rewards, values, dones, last_values, gamma, lam)
    d2 = 1.0 + gamma * 0.8 - 0.7
    d1 = 1.0 + gamma * 0.7 - 0.6
    d0 = 1.0 + gamma * 0.6 - 0.5
    a2 = d2
    a1 = d1 + gamma * lam * a2
    a0 = d0 + gamma * lam * a1
    np.testing.assert_allclose(adv[:, 0], [a0, a1, a2], rtol=1e-5)
    np.testing.assert_allclose(targets, adv + values, rtol=1e-6)
    # termination cuts the chain
    dones2 = np.array([[0.0], [1.0], [0.0]], np.float32)
    adv2, _ = compute_gae(rewards, values, dones2, last_values, gamma, lam)
    np.testing.assert_allclose(adv2[1, 0], 1.0 - 0.6, rtol=1e-5)


def test_ppo_learns_cartpole(ray_start_4cpu):
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=2, num_envs_per_env_runner=8,
                         rollout_fragment_length=64)
            .training(lr=3e-4, minibatch_size=128)
            .build())
    try:
        first = algo.train()
        assert first["num_env_steps_sampled"] == 2 * 8 * 64
        returns = [first["episode_return_mean"]]
        for _ in range(24):
            returns.append(algo.train()["episode_return_mean"])
        # CartPole random policy averages ~20; PPO must clearly learn.
        assert max(returns[-5:]) > 2 * returns[0], returns
        assert max(returns) >= 45, returns
    finally:
        algo.stop()


def test_ppo_as_tune_trainable(ray_start_4cpu, tmp_path):
    """Algorithm as a class Trainable: tune steps it and picks the best lr
    (reference Tuner(\"PPO\", param_space=...) path)."""
    from ray_tpu import tune
    from ray_tpu.train import RunConfig
    from ray_tpu.tune import TuneConfig, Tuner

    class PPOTrainable:
        def setup(self, config):
            self.algo = (PPOConfig()
                         .environment("CartPole-v1")
                         .env_runners(num_env_runners=1,
                                      num_envs_per_env_runner=8,
                                      rollout_fragment_length=32)
                         .training(lr=config["lr"], minibatch_size=64)
                         .build())

        def step(self):
            return self.algo.train()

    grid = Tuner(
        PPOTrainable,
        param_space={"lr": tune.grid_search([3e-4, 1e-6])},
        tune_config=TuneConfig(metric="episode_return_mean", mode="max"),
        run_config=RunConfig(storage_path=str(tmp_path),
                             stop={"training_iteration": 8}),
    ).fit()
    assert grid.num_errors == 0
    best = grid.get_best_result()
    assert best.config["lr"] == 3e-4  # the real lr beats the degenerate one


def test_impala_learns_cartpole(shutdown_only):
    """IMPALA improves CartPole return (reference
    rllib/algorithms/impala — BASELINE.md north-star workload). The async
    harvest loop keeps a sample in flight per runner; V-trace corrects the
    policy lag."""
    import ray_tpu
    from ray_tpu.rllib import IMPALAConfig

    ray_tpu.init(num_cpus=3)
    algo = (IMPALAConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=2, num_envs_per_env_runner=8,
                         rollout_fragment_length=64)
            .training(updates_per_iteration=4)
            .build())
    try:
        first = algo.train()
        assert first["num_env_steps_sampled"] == 4 * 64 * 8
        best = -1.0
        for _ in range(24):
            m = algo.train()
            r = m["episode_return_mean"]
            if r == r:  # not-NaN
                best = max(best, r)
        # Untrained CartPole hovers ~20; require clear learning signal
        # (the curve reaches ~65-70 by iteration 25 on this config).
        assert best > 55, f"IMPALA failed to learn: best return {best}"
    finally:
        algo.stop()


def test_prioritized_replay_buffer():
    """Priorities bias sampling toward high-TD transitions; IS weights and
    priority updates behave (reference prioritized_episode_buffer tests)."""
    from ray_tpu.rllib import PrioritizedReplayBuffer

    buf = PrioritizedReplayBuffer(capacity=100, alpha=1.0)
    buf.add_batch({"obs": np.arange(50, dtype=np.float32)[:, None],
                   "id": np.arange(50)})
    assert len(buf) == 50
    batch, idx, w = buf.sample(32, beta=0.4)
    assert batch["obs"].shape == (32, 1) and len(idx) == 32
    assert w.shape == (32,) and w.max() <= 1.0 + 1e-6
    # Crank priority of transition 7 way up: it should dominate samples.
    buf.update_priorities(np.arange(50), np.full(50, 1e-3))
    buf.update_priorities([7], [1e3])
    _, idx, w = buf.sample(256, beta=1.0)
    frac7 = float(np.mean(idx == 7))
    assert frac7 > 0.9, f"priority 7 sampled only {frac7:.0%}"
    # High-priority samples get the SMALLEST importance weights.
    assert w[np.asarray(idx) == 7].max() <= w.min() + 1e-6
    # circular overwrite keeps capacity bounded
    buf.add_batch({"obs": np.zeros((80, 1), np.float32),
                   "id": np.arange(80)})
    assert len(buf) == 100


def test_dqn_learns_cartpole(ray_start_4cpu):
    """DQN + double-Q + prioritized replay reaches the same regression bar
    style as PPO (reference tuned_examples/dqn cartpole)."""
    from ray_tpu.rllib import DQNConfig

    algo = (DQNConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                         rollout_fragment_length=64)
            .training(lr=5e-4, train_batch_size=128, num_learner_updates=24)
            .build())
    try:
        returns = []
        # Adaptive horizon: learning speed is seed-dependent; stop as soon
        # as the bar is reached, cap at 60 iterations.
        for _ in range(60):
            m = algo.train()
            r = m["episode_return_mean"]
            returns.append(r)
            if not np.isnan(r) and r >= 60:
                break
        assert m["num_transitions"] > 5000
        best = max(r for r in returns if not np.isnan(r))
        assert best >= 60, f"DQN failed to learn: returns {returns[-6:]}"
        # epsilon decayed
        assert m["epsilon"] < 0.3
    finally:
        algo.stop()


def test_multi_agent_env_runner_per_policy_batches(ray_start_2cpu):
    """MultiAgentEnvRunner maps agents to policy modules and returns
    per-MODULE batches; shared policies concatenate their agents' data."""
    from ray_tpu.rllib import (MultiAgentCartPole, MultiAgentEnvRunner,
                               RLModule, RLModuleSpec)
    import jax

    spec = RLModuleSpec(observation_dim=4, action_dim=2, hidden=(16,))
    # 3 agents, 2 policies: agents 0+2 SHARE policy_a.
    mapping = {"agent_0": "policy_a", "agent_1": "policy_b",
               "agent_2": "policy_a"}
    runner = MultiAgentEnvRunner(
        lambda n, seed=0: MultiAgentCartPole(n, 3, seed),
        num_envs=4, spec=spec, module_ids=["policy_a", "policy_b"],
        policy_mapping=mapping, seed=0)
    m = RLModule(spec)
    w = {"policy_a": m.init(jax.random.PRNGKey(0)),
         "policy_b": m.init(jax.random.PRNGKey(1))}
    runner.set_weights(w)
    out = runner.sample(10)
    assert set(out) == {"policy_a", "policy_b"}
    # policy_a serves 2 agents -> env axis 8; policy_b serves 1 -> 4
    assert out["policy_a"]["obs"].shape == (10, 8, 4)
    assert out["policy_b"]["obs"].shape == (10, 4, 4)
    assert out["policy_a"]["last_values"].shape == (8,)


def test_multi_agent_ppo_improves(ray_start_4cpu):
    """Per-policy PPO over a 2-agent env: both policies improve (learning
    regression in the style of the single-agent bar, shorter horizon)."""
    from ray_tpu.rllib import MultiAgentPPOConfig

    algo = (MultiAgentPPOConfig()
            .multi_agent(num_agents=2)
            .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                         rollout_fragment_length=64)
            .build())
    try:
        returns = []
        for _ in range(12):
            m = algo.train()
            returns.append(m["episode_return_mean"])
        assert m["num_env_steps_sampled"] == 2 * 2 * 4 * 64
        valid = [r for r in returns if not np.isnan(r)]
        assert max(valid[-4:]) > valid[0], returns
        assert max(valid) >= 30, returns
    finally:
        algo.stop()
