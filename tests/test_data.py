"""ray_tpu.data: public constructors, datasources, transforms, splits.

reference tests: python/ray/data/tests/test_consumption.py,
test_map.py, test_csv.py/test_parquet.py/test_json.py,
test_splitblocks.py, test_actor_pool_map_operator.py.
"""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


def test_from_items_and_range(ray_start_2cpu):
    ds = rd.from_items([{"x": i} for i in range(10)])
    assert ds.count() == 10
    assert sorted(r["x"] for r in ds.take_all()) == list(range(10))

    ds = rd.range(100, parallelism=4)
    assert ds.count() == 100
    assert ds.num_blocks() == 4
    assert ds.sum("id") == sum(range(100))

    dt = rd.range_tensor(8, shape=(2, 2))
    arr = dt.to_numpy("data")
    assert arr.shape == (8, 2, 2)


def test_map_filter_flatmap_pipeline(ray_start_2cpu):
    ds = (rd.range(50)
          .map(lambda r: {"id": r["id"] * 2})
          .filter(lambda r: r["id"] % 4 == 0)
          .flat_map(lambda r: [r, r]))
    rows = ds.take_all()
    assert len(rows) == 50  # 25 survivors, duplicated
    assert all(r["id"] % 4 == 0 for r in rows)


def test_map_batches_tasks_and_aggregates(ray_start_2cpu):
    ds = rd.range(40, parallelism=4).map_batches(
        lambda b: {"id": b["id"] + 1}, batch_size=8)
    assert ds.sum("id") == sum(range(1, 41))
    assert ds.min("id") == 1 and ds.max("id") == 40
    assert ds.mean("id") == pytest.approx(20.5)


class _AddState:
    """Callable class -> actor pool path; __init__ must run once per actor."""

    def __init__(self, delta):
        self.delta = delta
        self.pid = os.getpid()

    def __call__(self, batch):
        return {"id": batch["id"] + self.delta, "pid": np.full(len(batch["id"]), self.pid)}


def test_map_batches_actor_pool(ray_start_4cpu):
    ds = rd.range(32, parallelism=8).map_batches(
        _AddState, concurrency=2, fn_constructor_args=(100,))
    rows = ds.take_all()
    assert sorted(r["id"] for r in rows) == list(range(100, 132))
    pids = {r["pid"] for r in rows}
    assert 1 <= len(pids) <= 2  # ran on the pool's actors, not the driver
    assert os.getpid() not in pids


def test_read_write_csv_json_parquet(ray_start_2cpu, tmp_path):
    ds = rd.from_items([{"a": i, "b": float(i) * 0.5} for i in range(30)])

    pq_dir, csv_dir, js_dir = (str(tmp_path / d) for d in ("pq", "csv", "js"))
    ds.write_parquet(pq_dir)
    ds.write_csv(csv_dir)
    ds.write_json(js_dir)

    for reader, path in ((rd.read_parquet, pq_dir), (rd.read_csv, csv_dir),
                         (rd.read_json, js_dir)):
        back = reader(path)
        assert back.count() == 30, reader.__name__
        assert back.sum("a") == sum(range(30)), reader.__name__


def test_read_text_and_binary(ray_start_2cpu, tmp_path):
    p = tmp_path / "f.txt"
    p.write_text("alpha\nbeta\n\ngamma\n")
    ds = rd.read_text(str(p))
    assert ds.take_all() == [{"text": "alpha"}, {"text": "beta"}, {"text": "gamma"}]

    b = tmp_path / "blob.bin"
    b.write_bytes(b"\x00\x01\x02")
    bb = rd.read_binary_files(str(b), include_paths=True).take_all()
    assert bb[0]["bytes"] == b"\x00\x01\x02"
    assert bb[0]["path"].endswith("blob.bin")


def test_groupby(ray_start_2cpu):
    ds = rd.from_items([{"k": i % 3, "v": i} for i in range(12)])
    counts = {r["k"]: r["count()"] for r in ds.groupby("k").count().take_all()}
    assert counts == {0: 4, 1: 4, 2: 4}
    sums = {r["k"]: r["sum(v)"] for r in ds.groupby("k").sum("v").take_all()}
    assert sums == {0: 0 + 3 + 6 + 9, 1: 1 + 4 + 7 + 10, 2: 2 + 5 + 8 + 11}


def test_streaming_split_equal(ray_start_2cpu):
    # 3 shards over 10 rows: every shard must get EXACTLY 3 rows (remainder
    # dropped) or lockstep allreduce training hangs (round-2 advisor finding).
    ds = rd.range(10, parallelism=3)
    its = ds.streaming_split(3, equal=True)
    counts, seen = [], []
    for it in its:
        rows = list(it.iter_rows())
        counts.append(len(rows))
        seen.extend(r["id"] for r in rows)
    assert counts == [3, 3, 3]
    assert len(set(seen)) == 9  # no duplication across shards

    # equal=False keeps every row.
    its = ds.streaming_split(3, equal=False)
    total = sum(len(list(it.iter_rows())) for it in its)
    assert total == 10


def test_sort_shuffle_repartition_limit(ray_start_2cpu):
    ds = rd.from_items(list(range(20))).random_shuffle(seed=7)
    assert sorted(ds.take_all()) == list(range(20))
    s = rd.from_items([5, 3, 9, 1]).sort()
    assert s.take_all() == [1, 3, 5, 9]
    r = rd.range(16, parallelism=2).repartition(4)
    assert r.num_blocks() == 4 and r.count() == 16
    assert rd.range(100).limit(7).count() == 7


def test_data_to_train_e2e(ray_start_4cpu, tmp_path):
    """read -> map_batches -> streaming_split feeding JaxTrainer: equal
    shards, both workers see their shard via get_dataset_shard."""
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    csv_dir = str(tmp_path / "in")
    rd.from_items([{"x": float(i), "y": float(2 * i)} for i in range(64)]
                  ).write_csv(csv_dir)

    ds = rd.read_csv(csv_dir).map_batches(
        lambda b: {"x": b["x"] / 64.0, "y": b["y"] / 64.0})

    def loop(config):
        import numpy as np

        import ray_tpu.train as train

        it = train.get_dataset_shard("train")
        n = 0
        for batch in it.iter_batches(batch_size=8):
            assert batch["x"].shape == batch["y"].shape
            n += len(batch["x"])
        train.report({"rows": int(n)})

    trainer = JaxTrainer(
        loop, scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path / "runs")),
        datasets={"train": ds})
    result = trainer.fit()
    assert result.metrics["rows"] == 32  # 64 rows, equal split across 2


def test_store_backpressure_policy(shutdown_only):
    """Submissions pause while cluster shm usage is above the high-water
    mark (reference object-store-memory backpressure policy), and a
    pipeline larger than the store still completes (spill + streaming)."""
    import numpy as np

    from ray_tpu.data._internal import executor as ex

    # Tiny store: 8 blocks x 4MB through a 16MB store must stream/spill.
    ray_tpu.init(num_cpus=2, _system_config={
        "object_store_memory_bytes": 16 * 1024 * 1024})

    from ray_tpu._private.worker import global_worker

    w = global_worker()
    rep = w.io.run(w.controller.call("object_store_stats"), timeout=10)
    assert rep["capacity"] == 16 * 1024 * 1024

    ds = ray_tpu.data.range(8).map_batches(
        lambda b: {"x": np.ones((4 * 1024 * 1024 // 8,), np.float64)},
        batch_size=1)
    total = 0
    for batch in ds.iter_batches(batch_size=1):
        total += 1
    assert total >= 8

    # The policy itself: fill the agent-visible shm past the mark via a
    # worker-held object, then wait out a heartbeat so the controller sees
    # the agents' ground-truth shm usage.
    @ray_tpu.remote
    def hold():
        return np.ones(14 * 1024 * 1024, np.uint8)

    big = hold.remote()
    ray_tpu.wait([big], num_returns=1, timeout=60)
    import time

    time.sleep(1.5)  # > heartbeat_interval_s
    ex._bp_cache.update(t=0.0)
    assert ex._store_backpressured() is True
    del big


def test_distributed_sort_exchange(ray_start_2cpu):
    """Sample-based range-partitioned sort (reference sort_task_spec.py):
    many blocks, skewed values, ascending + descending, dict keys — and
    the driver must never materialize row payloads."""
    import random as _random

    import ray_tpu.data._internal.executor as ex

    rng = _random.Random(3)
    vals = [rng.randrange(10_000) for _ in range(400)] + [7] * 50
    ds = rd.from_items(vals, parallelism=8).sort()
    out = ds.take_all()
    assert out == sorted(vals)
    # descending
    d = rd.from_items(vals, parallelism=8).sort(descending=True).take_all()
    assert d == sorted(vals, reverse=True)
    # dict rows with a key column
    recs = [{"k": rng.randrange(100), "v": i} for i in range(200)]
    s = rd.from_items(recs, parallelism=4).sort(key=lambda r: r["k"])
    ks = [r["k"] for r in s.take_all()]
    assert ks == sorted(ks)
    # driver isolation: ray_tpu.get during the exchange must only carry
    # key samples / counts, never row payloads
    big = rd.from_items(list(range(2000)), parallelism=8)
    real_get = ray_tpu.get
    seen = []

    def spy_get(refs, timeout=None):
        out = real_get(refs, timeout=timeout)
        for o in out if isinstance(out, list) else [out]:
            if isinstance(o, list) and len(o) > 100:
                seen.append(len(o))
        return out

    ex.ray_tpu.get = spy_get
    try:
        sorted_ds = big.sort()
        blocks = sorted_ds._block_refs()
    finally:
        ex.ray_tpu.get = real_get
    assert not seen, f"driver pulled row payloads during sort: {seen}"
    rows = []
    for b in ray_tpu.get(blocks, timeout=600):
        rows.extend(b)
    assert rows == list(range(2000))


def test_distributed_shuffle_exchange(ray_start_2cpu):
    """Shuffle as a map/reduce exchange: permutation correctness, seed
    determinism, and no driver row materialization."""
    import ray_tpu.data._internal.executor as ex

    vals = list(range(500))
    a = rd.from_items(vals, parallelism=8).random_shuffle(seed=11).take_all()
    b = rd.from_items(vals, parallelism=8).random_shuffle(seed=11).take_all()
    c = rd.from_items(vals, parallelism=8).random_shuffle(seed=12).take_all()
    assert sorted(a) == vals and sorted(c) == vals
    assert a == b  # same seed -> same permutation
    assert a != c  # different seed -> different permutation
    assert a != vals  # actually shuffled
    real_get = ray_tpu.get
    seen = []

    def spy_get(refs, timeout=None):
        out = real_get(refs, timeout=timeout)
        for o in out if isinstance(out, list) else [out]:
            if isinstance(o, list) and len(o) > 100:
                seen.append(len(o))
        return out

    ex.ray_tpu.get = spy_get
    try:
        rd.from_items(vals, parallelism=8).random_shuffle(seed=5)._block_refs()
    finally:
        ex.ray_tpu.get = real_get
    assert not seen, f"driver pulled row payloads during shuffle: {seen}"


def test_pipelined_reduce_starts_before_last_map(ray_start_2cpu, monkeypatch):
    """The no-barrier core of the exchange (ISSUE 19 acceptance): with a
    small reduce fan-in, consolidation tasks must submit while map tasks
    are still in flight — progress ordering read from exchange_stats()."""
    from ray_tpu.data._internal import exchange as xch

    monkeypatch.setenv("RT_DATA_REDUCE_FANIN", "2")
    monkeypatch.setenv("RT_DATA_MAX_INFLIGHT_BLOCKS", "4")
    xch.reset_exchange_stats()
    items = [os.urandom(2048) for _ in range(256)]
    out = rd.from_items(items, parallelism=16).random_shuffle(seed=3).take_all()
    assert sorted(out) == sorted(items)
    st = xch.exchange_stats()
    assert st["maps_done"] == 16
    assert st["reduces_submitted"] > 16  # consolidations beyond the finals
    assert st["reduce_before_last_map"] == 1, (
        "no reduce-side merge submitted while maps were still in flight — "
        "the exchange ran as a barrier")


def test_exchange_spills_under_mem_cap(ray_start_2cpu, monkeypatch, tmp_path):
    """RT_DATA_MEM_CAP_BYTES forced low: consolidations spill through the
    storage plane, restore transparently at the final reduce, and the
    output is still a correct permutation. The driver emits one data_spill
    event with byte accounting."""
    from ray_tpu.data._internal import exchange as xch
    from ray_tpu.util import state

    monkeypatch.setenv("RT_DATA_MEM_CAP_BYTES", "1")
    monkeypatch.setenv("RT_DATA_REDUCE_FANIN", "2")
    monkeypatch.setenv("RT_DATA_SPILL_URI", "local://" + str(tmp_path / "sp"))
    xch.reset_exchange_stats()
    items = [os.urandom(1024) for _ in range(128)]
    out = rd.from_items(items, parallelism=8).random_shuffle(seed=9).take_all()
    assert sorted(out) == sorted(items)
    # Driver-side accounting: spills are counted once, from the resolved
    # consolidation metas (a worker-side bump would be invisible here).
    st = xch.exchange_stats()
    assert st["spilled_parts"] > 0
    assert st["spilled_bytes"] > 0
    import time

    deadline = time.monotonic() + 10
    evs = state.list_events(kind="data_spill")
    while not evs and time.monotonic() < deadline:
        time.sleep(0.1)
        evs = state.list_events(kind="data_spill")
    assert evs, "mem-cap-forced spill emitted no data_spill event"
    assert evs[-1]["attrs"]["bytes"] > 0
    assert evs[-1]["attrs"]["scheme"] == "local"
    # Restores self-delete their backing files: the spill dir self-cleans.
    leftovers = [f for _r, _d, fs in os.walk(str(tmp_path / "sp")) for f in fs]
    assert leftovers == [], f"spilled shards not cleaned up: {leftovers}"


def test_exchange_at_scale_64_blocks(ray_start_2cpu):
    """64-block shuffle/repartition/sort (ISSUE 19 satellite): permutation
    and order correctness at a block count where mid-wave consolidation,
    windowed submission, and per-partition merge ordering all engage."""
    n = 1024
    vals = list(range(n))
    sh = rd.from_items(vals, parallelism=64).random_shuffle(seed=21)
    out = sh.take_all()
    assert sorted(out) == vals and out != vals
    rp = rd.from_items(vals, parallelism=64).repartition(16)
    assert rp.num_blocks() == 16
    assert rp.take_all() == vals  # contiguous repartition preserves order
    so = rd.from_items(vals[::-1], parallelism=64).sort()
    assert so.take_all() == vals


def test_shuffle_per_partition_determinism(ray_start_2cpu):
    """Fixed seed -> byte-identical output PER BLOCK, not just as a
    multiset: the map slicing, partition assignment, and per-partition
    finalize seed are all derived from (seed, index), independent of
    completion order."""
    items = [os.urandom(64) for _ in range(512)]

    def blocks(seed):
        refs = rd.from_items(items, parallelism=16).random_shuffle(
            seed=seed)._block_refs()
        return [ray_tpu.get(r, timeout=600) for r in refs]

    a, b = blocks(5), blocks(5)
    assert a == b
    assert blocks(6) != a


def test_barrier_mode_output_identical(ray_start_2cpu, monkeypatch):
    """RT_DATA_PIPELINED_EXCHANGE=0 (the bench's barrier A/B leg) must
    produce byte-identical blocks: pipelining is a scheduling change, not
    a semantic one."""
    items = [os.urandom(64) for _ in range(256)]

    def blocks(seed):
        refs = rd.from_items(items, parallelism=8).random_shuffle(
            seed=seed)._block_refs()
        return [ray_tpu.get(r, timeout=600) for r in refs]

    monkeypatch.setenv("RT_DATA_PIPELINED_EXCHANGE", "1")
    pipelined = blocks(13)
    monkeypatch.setenv("RT_DATA_PIPELINED_EXCHANGE", "0")
    barrier = blocks(13)
    assert pipelined == barrier


def test_iter_batches_streams_with_bounded_lookahead(ray_start_2cpu,
                                                     monkeypatch):
    """iter_batches over an unexecuted shuffle plan streams reduce outputs
    without driver materialization: the unconsumed-block high-water mark
    stays within RT_DATA_MAX_INFLIGHT_BLOCKS, and a fully drained stream
    caches the refs so the second pass doesn't re-execute."""
    from ray_tpu.data._internal import exchange as xch

    monkeypatch.setenv("RT_DATA_MAX_INFLIGHT_BLOCKS", "4")
    xch.reset_exchange_stats()
    ds = rd.range(4096, parallelism=32).random_shuffle(seed=2)
    assert ds._cached_refs is None
    seen = []
    for batch in ds.iter_batches(batch_size=256):
        seen.extend(int(v) for v in batch["id"])
    assert sorted(seen) == list(range(4096))
    st = xch.exchange_stats()
    assert 0 < st["stream_max_ahead"] <= 4, st
    # Full drain cached the refs: second pass rides them, same rows.
    assert ds._cached_refs is not None
    again = []
    for batch in ds.iter_batches(batch_size=256):
        again.extend(int(v) for v in batch["id"])
    assert again == seen


def test_read_tasks_sized_by_block_bytes(tmp_path, monkeypatch):
    """FileBasedDatasource groups files into RT_DATA_BLOCK_BYTES-target
    read tasks: many small files pack into one task, one oversized
    splittable file cuts into row-range slices, and unsplittable (binary)
    files stay whole."""
    from ray_tpu.data.datasource import BinaryDatasource, TextDatasource

    small = tmp_path / "small"
    small.mkdir()
    for i in range(8):
        (small / f"f{i}.txt").write_text("".join(
            f"s{i}-{j}\n" for j in range(10)))  # ~60B each
    sz = os.path.getsize(str(small / "f0.txt"))
    monkeypatch.setenv("RT_DATA_BLOCK_BYTES", str(2 * sz + 1))
    tasks = TextDatasource(str(small)).get_read_tasks(parallelism=1)
    assert len(tasks) == 4  # 8 files packed 2 per ~2-file-sized block
    rows = [r for t in tasks for r in t()["text"]]
    assert len(rows) == 80 and rows[0] == "s0-0"

    big = tmp_path / "big.txt"
    big.write_text("".join(f"line-{j:04d}\n" for j in range(300)))
    target = os.path.getsize(str(big)) // 3 + 1
    monkeypatch.setenv("RT_DATA_BLOCK_BYTES", str(target))
    tasks = TextDatasource(str(big)).get_read_tasks(parallelism=1)
    assert len(tasks) == 3  # oversized file split into row-range slices
    rows = [r for t in tasks for r in t()["text"]]
    assert rows == [f"line-{j:04d}" for j in range(300)]

    blob = tmp_path / "whole.bin"
    blob.write_bytes(os.urandom(4096))
    monkeypatch.setenv("RT_DATA_BLOCK_BYTES", "512")
    tasks = BinaryDatasource(str(blob)).get_read_tasks(parallelism=1)
    assert len(tasks) == 1  # unsplittable: one row per whole file
    assert tasks[0]()["bytes"][0] == blob.read_bytes()


def test_batch_format_preserves_trailing_nul_bytes():
    """numpy's fixed-width S dtype treats trailing NULs as padding and
    strips them on element access, so a bytes row ending in b"\\x00" used
    to come out of iter_batches one byte short. Batch columns built from
    bytes/str rows must use object dtype (caught by an end-to-end drive:
    ~1 in 256 os.urandom rows ends with a NUL)."""
    from ray_tpu.data.block import BlockAccessor, combine_blocks

    rows = [b"ab\x00", b"\x00\x00", b"xy"]
    batch = BlockAccessor.for_block(rows).to_batch()
    assert [bytes(x) for x in batch["item"]] == rows

    dict_rows = [{"k": r} for r in rows]
    batch = BlockAccessor.for_block(dict_rows).to_batch()
    assert [bytes(x) for x in batch["k"]] == rows

    merged = combine_blocks([{"k": rows[:2]}, {"k": rows[2:]}])
    assert [bytes(x) for x in merged["k"]] == rows

    strs = ["a\x00", "\x00"]
    batch = BlockAccessor.for_block(strs).to_batch()
    assert list(batch["item"]) == strs
