"""Chaos: device object plane under producer failure and ref churn.

Pins the two acceptance behaviors of README "Device objects":
- killing the producing actor mid-pipeline makes the consumer's get()
  raise a clean ObjectLostError NAMING the lost producer — never a hang;
- owner-side frees actually reach the producer's DeviceObjectTable
  (controller -> node agent -> device_free fan-out), so churning refs
  leaves no pinned-array leak.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import exceptions as exc

N = 1 << 18  # 1MB float32 — well past inline and device thresholds


@ray_tpu.remote(num_cpus=0)
class Producer:
    def make(self, i):
        import jax.numpy as jnp

        return jnp.full((N,), float(i), jnp.float32)

    def stats(self):
        from ray_tpu.experimental import device_objects

        return device_objects.device_object_stats()


def test_producer_death_raises_object_lost(ray_start_2cpu, device_plane_cpu):
    """Kill the producing actor BEFORE the consumer reads: get() must fail
    fast with ObjectLostError (the value only ever lived in the dead
    actor's device memory), not hang waiting on a dead address."""
    p = Producer.remote()
    ref = p.make.remote(5)
    done, _ = ray_tpu.wait([ref], num_returns=1, timeout=60)
    assert done, "producer never finished"
    ray_tpu.kill(p)
    time.sleep(1.0)  # let the kill land and the lost sweep run
    t0 = time.monotonic()
    with pytest.raises(exc.ObjectLostError) as ei:
        ray_tpu.get(ref, timeout=30)
    assert time.monotonic() - t0 < 20, "get() hung instead of failing fast"
    assert "lost" in str(ei.value)
    # A second get fails the same way (the failure is sticky, not racy).
    with pytest.raises(exc.ObjectLostError):
        ray_tpu.get(ref, timeout=10)


def test_producer_death_after_export_keeps_consumers_alive(
        ray_start_2cpu, device_plane_cpu):
    """A consumer that ALREADY materialized the object (forcing the shm
    export) keeps working after the producer dies — the exported copy
    outlives the producer for reads the driver already resolved."""
    p = Producer.remote()
    ref = p.make.remote(3)
    got = ray_tpu.get(ref, timeout=60)  # forces the tier-1 export
    ray_tpu.kill(p)
    time.sleep(0.5)
    assert float(np.asarray(got).sum()) == 3.0 * N  # live view stays valid


def test_freed_refs_empty_table_no_leak(ray_start_2cpu, device_plane_cpu):
    """100 produce/consume/free iterations: the producer's
    DeviceObjectTable must drain back to empty (owner-tracked frees reach
    the producing worker), not grow by one pinned array per iteration."""
    p = Producer.remote()
    high_water = 0
    for i in range(100):
        ref = p.make.remote(i)
        if i % 10 == 0:  # exercise the export/free path too, cheaply
            got = ray_tpu.get(ref, timeout=60)
            assert float(np.asarray(got)[0]) == float(i)
            del got
        del ref
        if i % 25 == 24:
            high_water = max(high_water, ray_tpu.get(
                p.stats.remote(), timeout=60)["count"])
    # Frees are coalesced (owner flush -> controller -> agent -> worker):
    # poll for the drain rather than asserting instantaneously.
    deadline = time.monotonic() + 30
    stats = None
    while time.monotonic() < deadline:
        stats = ray_tpu.get(p.stats.remote(), timeout=60)
        if stats["count"] == 0:
            break
        time.sleep(0.3)
    assert stats == {"count": 0, "bytes": 0}, (
        f"device object table leaked: {stats} (high water {high_water})")
