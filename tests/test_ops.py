"""Attention kernels: Pallas flash (interpret mode on CPU) and ring
attention over a virtual sp mesh axis, both vs the XLA reference.

reference has no attention kernels (delegates to torch/vLLM); these are
TPU-native and tested against ray_tpu.ops.attention._xla_attention.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import _xla_attention
from ray_tpu.ops.flash_attention import flash_attention
from ray_tpu.ops.ring_attention import ring_attention


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_numerics(causal):
    rng = np.random.RandomState(0)
    b, sq, sk, h, d = 2, 256, 256, 4, 64
    q = jnp.asarray(rng.randn(b, sq, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, sk, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, sk, h, d), jnp.float32)
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128,
                          interpret=True)
    ref = _xla_attention(q, k, v, causal=causal)
    assert float(jnp.max(jnp.abs(out - ref))) < 2e-5


def test_flash_attention_gqa_and_cross_lengths():
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 128, 8, 64), jnp.float32)
    k = jnp.asarray(rng.randn(1, 384, 2, 64), jnp.float32)
    v = jnp.asarray(rng.randn(1, 384, 2, 64), jnp.float32)
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128,
                          interpret=True)
    ref = _xla_attention(q, k, v, causal=True)
    assert float(jnp.max(jnp.abs(out - ref))) < 2e-5


def test_flash_attention_rejects_bad_shapes():
    q = jnp.zeros((1, 100, 4, 64))  # 100 not divisible by any block
    with pytest.raises(ValueError):
        flash_attention(q, q, q, block_q=128, block_k=128, interpret=True)


def test_flash_attention_accepts_bench_shape():
    """The microbench config (b4 s2048 h8 d128) must pass block-shape
    selection — auto-derived lane-aligned blocks, no ValueError (r05
    regression: a hard-coded block pair rejected the flagship shape and the
    bench silently fell back to XLA)."""
    b, s, h, d = 4, 2048, 8, 128
    q = jax.ShapeDtypeStruct((b, s, h, d), jnp.bfloat16)
    # eval_shape traces the full kernel call (shape checks + pallas_call
    # spec construction) without paying the interpret-mode compute.
    out = jax.eval_shape(
        lambda q, k, v: flash_attention(q, k, v, causal=True,
                                        interpret=True), q, q, q)
    assert out.shape == (b, s, h, d)


def test_flash_attention_block_derivation_clamps_to_valid_tiles():
    """derive_blocks is the single derivation path (auto AND explicit
    preferences): the bench shape must land on the tuned 512/1024, explicit
    oversized blocks clamp to aligned divisors instead of slipping through
    min() as tile-violating remnants (r05: 'blocks 8/8 violate TPU
    tiling'), and infeasible shapes raise the fallback reason."""
    from ray_tpu.ops.flash_attention import derive_blocks

    # The microbench shape (b4 s2048 h8 d128) selects the Pallas path with
    # the tuned blocks.
    assert derive_blocks(2048, 2048) == (512, 1024)
    # Explicit blocks are preferences: clamped to aligned divisors.
    assert derive_blocks(256, 256, 1024, 1024) == (256, 256)
    assert derive_blocks(2048, 2048, 100, 1000) == (64, 512)
    # A short k sequence can never produce a sub-128 block_k: it raises
    # (XLA fallback) with the reason, not a tile-violating 8/8 pair.
    with pytest.raises(ValueError, match="lane tile"):
        derive_blocks(8, 8)
    with pytest.raises(ValueError, match="lane tile"):
        derive_blocks(256, 64, 128, 128)
    with pytest.raises(ValueError, match="sublane tile"):
        derive_blocks(100, 256)


def test_flash_attention_explicit_blocks_clamped_numerics():
    """An explicit block preference larger than the sequence still runs
    (clamped), matching the XLA reference."""
    rng = np.random.RandomState(7)
    b, s, h, d = 1, 256, 2, 32
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    out = flash_attention(q, q, q, causal=True, block_q=512, block_k=1024,
                          interpret=True)
    ref = _xla_attention(q, q, q, causal=True)
    assert float(jnp.max(jnp.abs(out - ref))) < 2e-5


def test_flash_attention_auto_blocks():
    """Auto-derived blocks: lane-aligned divisors of Sq/Sk, numerics still
    matching the XLA reference; shapes with no aligned divisor raise."""
    from ray_tpu.ops.flash_attention import _auto_block

    assert _auto_block(2048, 512, 8) == 512
    assert _auto_block(2048, 1024, 128) == 1024
    assert _auto_block(640, 512, 8) == 320
    assert _auto_block(640, 1024, 128) == 640
    assert _auto_block(16, 512, 8) == 16
    assert _auto_block(64, 1024, 128) is None  # < one lane tile
    assert _auto_block(100, 512, 8) is None  # not sublane-alignable
    rng = np.random.RandomState(3)
    b, s, h, d = 1, 256, 2, 64
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    out = flash_attention(q, q, q, causal=True, interpret=True)  # auto blocks
    ref = _xla_attention(q, q, q, causal=True)
    assert float(jnp.max(jnp.abs(out - ref))) < 2e-5


def test_attention_fallback_warns_per_reason(caplog):
    """A second, DIFFERENT shape rejection must warn too (the old
    once-per-process flag swallowed it); the same reason stays deduped."""
    import logging

    from ray_tpu.ops import attention as attn_mod
    from ray_tpu.ops.attention import dot_product_attention

    attn_mod._warned_reasons.clear()
    q_bad_sq = jnp.zeros((1, 100, 2, 64), jnp.float32)  # Sq not 8-alignable
    q_small = jnp.zeros((1, 64, 2, 64), jnp.float32)  # Sk < one lane tile
    with caplog.at_level(logging.WARNING, logger="ray_tpu.ops.attention"):
        dot_product_attention(q_bad_sq, q_bad_sq, q_bad_sq, use_pallas=True)
        first = [r for r in caplog.records if "falling back" in r.message]
        dot_product_attention(q_small, q_small, q_small, use_pallas=True)
        second = [r for r in caplog.records if "falling back" in r.message]
        # repeat of the first reason: deduped
        dot_product_attention(q_bad_sq, q_bad_sq, q_bad_sq, use_pallas=True)
        third = [r for r in caplog.records if "falling back" in r.message]
    assert len(first) == 1
    assert len(second) == 2, "second distinct reason was swallowed"
    assert len(third) == 2, "duplicate reason was not deduped"


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_full(causal):
    """4-way sp sharding on the CPU mesh: ring attention must equal
    single-device attention on the gathered sequence."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("sp",))
    rng = np.random.RandomState(2)
    b, s, h, d = 2, 64, 2, 16
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)

    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="sp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
    )
    out = jax.jit(ring)(q, k, v)
    ref = _xla_attention(q, k, v, causal=causal)
    assert float(jnp.max(jnp.abs(out - ref))) < 2e-5


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_attention_matches_full(causal):
    """4-way Ulysses sequence parallelism (all-to-all head sharding) must
    equal single-device attention on the gathered sequence."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from ray_tpu.ops.ulysses import ulysses_attention

    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("sp",))
    rng = np.random.RandomState(5)
    b, s, h, d = 2, 64, 4, 16  # h divisible by the 4-way axis
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)

    uly = shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, axis_name="sp",
                                          causal=causal),
        mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
    )
    out = jax.jit(uly)(q, k, v)
    ref = _xla_attention(q, k, v, causal=causal)
    assert float(jnp.max(jnp.abs(out - ref))) < 2e-5
