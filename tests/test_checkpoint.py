"""Async sharded checkpoint engine (README "Checkpointing & storage"):
save_async/restore round trips, resharding restore (save on a 4-way mesh,
restore onto 2 and 8), manifest-last commit, multi-rank storage-mediated
commit barrier, retention + pins, partial GC, digest verification, and
RT_CKPT_ASYNC=0 byte-identical sync semantics.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from ray_tpu import storage
from ray_tpu._private.rtconfig import CONFIG
from ray_tpu.train import checkpoint as ck
from ray_tpu.train.checkpoint import Checkpoint

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("dp",))


def _transformer_state(mesh):
    """A small transformer-shaped param tree, dim-0 sharded over `mesh`
    (divisible by 8 so the same tree reshards onto 2/4/8 devices)."""
    sh = NamedSharding(mesh, P("dp"))
    rep = NamedSharding(mesh, P())
    rng = np.random.RandomState(0)

    def dev(a, s):
        return jax.device_put(jnp.asarray(a), s)

    return {
        "params": {
            "embed": dev(rng.rand(16, 8).astype("float32"), sh),
            "attn": {"wq": dev(rng.rand(8, 8).astype("float32"), sh),
                     "wk": dev(rng.rand(8, 8).astype("float32"), sh),
                     "wo": dev(rng.rand(8, 8).astype("float32"), sh)},
            "mlp": (dev(rng.rand(8, 32).astype("float32"), sh),
                    dev(rng.rand(32, 8).astype("float32"), sh)),
            "ln_scale": dev(np.ones(8, "float32"), rep),
        },
        "opt_mu": {"embed": dev(rng.rand(16, 8).astype("float32"), sh)},
        "step": 41,
        "meta": {"lr": 3e-4, "name": "tiny"},
    }


def _leaf_arrays(state):
    out = {}

    def walk(t, p):
        if isinstance(t, dict):
            for k, v in t.items():
                walk(v, p + (str(k),))
        elif isinstance(t, (list, tuple)):
            for i, v in enumerate(t):
                walk(v, p + (str(i),))
        elif isinstance(t, (np.ndarray, jax.Array)):
            out["/".join(p)] = np.asarray(t)

    walk(state, ())
    return out


def test_roundtrip_numpy_tree(tmp_path):
    state = {"a": np.arange(12.0).reshape(3, 4), "b": [1, "two", 3.0],
             "nested": {"c": np.ones(5, "int32")}, "none": None}
    d = str(tmp_path / "ck1")
    h = ck.save_async(state, d, step=1)
    info = h.result(30)
    assert info["kind"] == "state" and info["step"] == 1
    st = ck.restore(d)
    assert np.array_equal(st["a"], state["a"])
    assert st["b"] == [1, "two", 3.0] and st["none"] is None
    assert np.array_equal(st["nested"]["c"], state["nested"]["c"])


def test_manifest_is_the_commit_point(tmp_path):
    d = str(tmp_path / "ck")
    ck.save(np.arange(4.0), d, step=1)
    man = ck.load_manifest(d)
    assert man is not None and man["format"] == 1
    # Removing ONLY the manifest makes the checkpoint invisible/partial.
    storage.delete(storage.join(d, ck.MANIFEST))
    with pytest.raises(storage.StorageNotFoundError):
        ck.restore(d)
    assert ck.latest_checkpoint(str(tmp_path)) is None


@pytest.mark.parametrize("target_n", [2, 8])
def test_resharding_roundtrip_4_to_n(tmp_path, target_n):
    """Acceptance: save a sharded transformer state on a 4-way mesh,
    restore onto 2- and 8-way meshes — every parameter leaf bitwise
    equal, and the restored arrays really live on the new mesh."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the virtual 8-device CPU mesh")
    src = _transformer_state(_mesh(4))
    want = _leaf_arrays(src)
    d = str(tmp_path / "ck")
    ck.save_async(src, d, step=3).result(60)

    tgt_mesh = _mesh(target_n)
    st = ck.restore(d, mesh=tgt_mesh,
                    shardings=lambda p, shape, dt:
                    P("dp") if shape and shape[0] % target_n == 0 else P())
    got = _leaf_arrays(st)
    assert set(got) == set(want)
    for p in want:
        assert np.array_equal(got[p], want[p]), f"leaf {p} differs"
    assert st["step"] == 41 and st["meta"]["name"] == "tiny"
    # really resharded: the embed leaf spans target_n devices now
    emb = st["params"]["embed"]
    assert len(emb.sharding.device_set) == target_n
    # ...and each host shard only covers 1/target_n of dim 0
    assert emb.addressable_shards[0].data.shape[0] == 16 // target_n


def test_restore_without_shardings_gives_numpy(tmp_path):
    src = _transformer_state(_mesh(4))
    d = str(tmp_path / "ck")
    ck.save(src, d)
    st = ck.restore(d)
    assert isinstance(st["params"]["embed"], np.ndarray)
    assert np.array_equal(st["params"]["embed"],
                          np.asarray(src["params"]["embed"]))


def test_sync_async_byte_identical(tmp_path, monkeypatch):
    """RT_CKPT_ASYNC=0 restores synchronous-save semantics with the SAME
    bytes: identical file sets and content digests."""
    state = {"w": np.arange(64.0).reshape(8, 8), "step": 9}
    d_async = str(tmp_path / "a")
    d_sync = str(tmp_path / "s")
    h = ck.save_async(state, d_async, step=9)
    h.result(30)
    assert h.stats.get("retries", 0) == 0
    monkeypatch.setitem(CONFIG._overrides, "ckpt_async", False)
    h2 = ck.save_async(state, d_sync, step=9)
    assert h2.done()  # inline: already committed on return
    files = lambda m: {s["file"]: s["sha1"]  # noqa: E731
                       for l in m["leaves"] for s in l["shards"]}
    m1, m2 = ck.load_manifest(d_async), ck.load_manifest(d_sync)
    assert files(m1) == files(m2)
    assert m1["tree_sha1"] == m2["tree_sha1"]
    assert m1["bytes"] == m2["bytes"]


def test_multirank_commit_barrier(tmp_path):
    """The commit barrier rides storage: rank 0 must NOT commit until
    every rank's shard metadata has landed; a checkpoint with a missing
    rank stays partial (and times out)."""
    state = {"w": np.arange(8.0)}
    d = str(tmp_path / "ck")
    committed = threading.Event()

    def rank0():
        ck.save(state, d, step=1, rank=0, world_size=2)
        committed.set()

    t = threading.Thread(target=rank0, daemon=True)
    t.start()
    time.sleep(0.4)
    assert not committed.is_set(), "rank 0 committed without rank 1"
    assert ck.load_manifest(d) is None
    ck.save(state, d, step=1, rank=1, world_size=2)
    t.join(30)
    assert committed.is_set()
    man = ck.load_manifest(d)
    assert man is not None and man["world_size"] == 2


def test_multirank_commit_timeout(tmp_path, monkeypatch):
    monkeypatch.setitem(CONFIG._overrides, "ckpt_commit_timeout_s", 0.3)
    d = str(tmp_path / "ck")
    with pytest.raises(TimeoutError):
        ck.save({"w": np.arange(4.0)}, d, step=1, rank=0, world_size=2)
    assert ck.load_manifest(d) is None  # never committed


def test_retention_keeps_last_k_and_pins(tmp_path, monkeypatch):
    parent = str(tmp_path / "cks")
    dirs = [storage.join(parent, f"checkpoint_{i:06d}") for i in range(5)]
    for i, d in enumerate(dirs):
        ck.save({"w": np.full(4, float(i))}, d, step=i)
    ck.pin(dirs[0], owner="trial-clone")
    deleted = ck.retention(parent, keep=2)
    # oldest 3 are victims, but dirs[0] is pinned and survives
    assert set(deleted) == {dirs[1], dirs[2]}
    rows = ck.list_checkpoints(parent)
    assert [r["uri"] for r in rows] == [dirs[0], dirs[3], dirs[4]]
    assert rows[0]["pins"] == ["trial-clone"]
    # the pinned checkpoint still restores bitwise
    st = ck.restore(dirs[0])
    assert np.array_equal(st["w"], np.zeros(4))
    ck.unpin(dirs[0], owner="trial-clone")
    assert ck.retention(parent, keep=2) == [dirs[0]]


def test_env_keep_runs_retention_on_commit(tmp_path, monkeypatch):
    monkeypatch.setitem(CONFIG._overrides, "ckpt_keep", 2)
    monkeypatch.setitem(CONFIG._overrides, "ckpt_partial_grace_s", 600.0)
    parent = str(tmp_path / "cks")
    for i in range(4):
        d = storage.join(parent, f"checkpoint_{i:06d}")
        ck.save_async({"w": np.full(2, float(i))}, d, step=i).result(30)
    rows = [r for r in ck.list_checkpoints(parent) if r["committed"]]
    assert len(rows) == 2 and rows[-1]["step"] == 3


def test_retention_orders_by_commit_time_across_restarts(tmp_path):
    """The train session's step counter resets on restart: a post-restart
    checkpoint (step 1) committed AFTER the pre-crash step 3 is the run's
    latest — retention must keep it and collect the stale one."""
    parent = str(tmp_path / "cks")
    pre = storage.join(parent, "checkpoint_r0_000003")
    post = storage.join(parent, "checkpoint_r1_000001")
    ck.save({"w": np.full(2, 3.0)}, pre, step=3)
    ck.save({"w": np.full(2, 1.0)}, post, step=1)  # committed later
    assert ck.latest_checkpoint(parent) == post
    assert ck.retention(parent, keep=1) == [pre]
    assert np.array_equal(ck.restore(post)["w"], np.full(2, 1.0))


def test_snapshot_copies_host_views_for_donation_safety(tmp_path):
    """Host-view snapshots must not alias jax buffer memory by default —
    XLA donation could free it mid-write (RT_CKPT_SNAPSHOT_COPY=0 is the
    opt-out for donation-free loops)."""
    x = jnp.arange(32, dtype=jnp.float32)
    leaf = ck._snapshot_leaf("w", x)
    nd = leaf["shards"][0]["data"]
    assert nd.flags["OWNDATA"], "snapshot aliases the jax buffer"
    assert np.array_equal(nd, np.arange(32, dtype=np.float32))


def test_gc_partials_respects_grace(tmp_path):
    parent = str(tmp_path / "cks")
    good = storage.join(parent, "checkpoint_000001")
    ck.save({"w": np.arange(3.0)}, good, step=1)
    # Fabricate a partial: in-progress marker, shard file, NO manifest.
    part = storage.join(parent, "checkpoint_000002")
    storage.put(storage.join(part, "_inprogress_r0"),
                json.dumps({"start": time.time(), "rank": 0,
                            "world": 1}).encode())
    storage.put(storage.join(part, "a0000_000_r0.bin"), b"garbage")
    assert ck.gc_partials(parent, grace_s=600) == []  # young: presumed live
    assert ck.gc_partials(parent, grace_s=0) == [part]
    assert storage.listdir(part) == []
    # the committed neighbor is untouched
    assert np.array_equal(ck.restore(good)["w"], np.arange(3.0))


def test_restore_detects_corruption(tmp_path):
    d = str(tmp_path / "ck")
    ck.save({"w": np.arange(16.0)}, d, step=1)
    man = ck.load_manifest(d)
    victim = man["leaves"][0]["shards"][0]["file"]
    blob = bytearray(storage.get_bytes(storage.join(d, victim)))
    blob[-1] ^= 0xFF
    storage.put(storage.join(d, victim), bytes(blob))
    with pytest.raises(storage.StorageError, match="digest"):
        ck.restore(d)
    # verify=False trusts the bytes (operator escape hatch)
    ck.restore(d, verify=False)


def test_checkpoint_class_materializes_nonlocal(tmp_path):
    from ray_tpu.storage.mem import MemBackend

    MemBackend.clear_all()
    src = tmp_path / "src"
    src.mkdir()
    (src / "state.pkl").write_bytes(b"payload")
    (src / "sub").mkdir()
    (src / "sub" / "x.txt").write_bytes(b"nested")
    ck.upload_directory(str(src), "mem://ckpts/one", step=1)
    c = Checkpoint("mem://ckpts/one")
    with c.as_directory() as d:
        assert open(os.path.join(d, "state.pkl"), "rb").read() == b"payload"
        assert open(os.path.join(d, "sub", "x.txt"), "rb").read() == b"nested"
    # local checkpoints keep the zero-copy yield
    c2 = Checkpoint(str(src))
    with c2.as_directory() as d2:
        assert os.path.samefile(d2, str(src))
    MemBackend.clear_all()


def test_engine_over_mem_backend(tmp_path):
    """The whole engine runs against a non-filesystem backend."""
    from ray_tpu.storage.mem import MemBackend

    MemBackend.clear_all()
    d = "mem://engine/checkpoint_000001"
    ck.save({"w": np.arange(6.0), "tag": "m"}, d, step=1)
    st = ck.restore(d)
    assert np.array_equal(st["w"], np.arange(6.0)) and st["tag"] == "m"
    assert ck.latest_checkpoint("mem://engine") == d
    MemBackend.clear_all()


def test_namedtuple_and_scalar_leaves(tmp_path):
    import collections

    Opt = collections.namedtuple("Opt", ["mu", "nu"])
    state = {"opt": Opt(np.arange(4.0), np.arange(2.0)),
             "scalar": np.float32(7.5)}
    d = str(tmp_path / "ck")
    ck.save(state, d)
    st = ck.restore(d)
    assert type(st["opt"]).__name__ == "Opt"
    assert np.array_equal(st["opt"].mu, np.arange(4.0))
    assert st["scalar"] == np.float32(7.5)
