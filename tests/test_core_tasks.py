"""Task submission/execution (parity: reference python/ray/tests/test_basic*.py)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import TaskError


def test_simple_task(ray_start_2cpu):
    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get(f.remote(1), timeout=30) == 2


def test_many_tasks(ray_start_2cpu):
    @ray_tpu.remote
    def sq(x):
        return x * x

    refs = [sq.remote(i) for i in range(20)]
    assert ray_tpu.get(refs, timeout=60) == [i * i for i in range(20)]


def test_kwargs_and_defaults(ray_start_2cpu):
    @ray_tpu.remote
    def g(a, b=10, *, c=100):
        return a + b + c

    assert ray_tpu.get(g.remote(1), timeout=30) == 111
    assert ray_tpu.get(g.remote(1, b=2, c=3), timeout=30) == 6


def test_multiple_returns(ray_start_2cpu):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    r1, r2, r3 = three.remote()
    assert ray_tpu.get([r1, r2, r3], timeout=30) == [1, 2, 3]


def test_task_exception_propagates(ray_start_2cpu):
    @ray_tpu.remote
    def boom():
        raise ValueError("bad input")

    with pytest.raises(TaskError, match="bad input"):
        ray_tpu.get(boom.remote(), timeout=30)


def test_ref_as_arg_inlined(ray_start_2cpu):
    @ray_tpu.remote
    def plus(a, b):
        return a + b

    r1 = plus.remote(1, 2)
    r2 = plus.remote(r1, 10)  # dependency on another task's output
    assert ray_tpu.get(r2, timeout=30) == 13


def test_large_arg_and_return(ray_start_2cpu):
    @ray_tpu.remote
    def double(a):
        return a * 2

    arr = np.arange(500_000, dtype=np.float64)
    out = ray_tpu.get(double.remote(arr), timeout=60)
    np.testing.assert_array_equal(out, arr * 2)


def test_nested_tasks(ray_start_2cpu):
    @ray_tpu.remote
    def inner(x):
        return x * 10

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x), timeout=30) + 1

    assert ray_tpu.get(outer.remote(4), timeout=60) == 41


def test_ref_inside_container(ray_start_2cpu):
    @ray_tpu.remote
    def deref(lst):
        # lst contains a borrowed ObjectRef; the task gets it explicitly
        return ray_tpu.get(lst[0], timeout=30) + lst[1]

    r = ray_tpu.put(5)
    assert ray_tpu.get(deref.remote([r, 7]), timeout=60) == 12


def test_options_override(ray_start_2cpu):
    @ray_tpu.remote(num_cpus=2)
    def f():
        return "ok"

    assert ray_tpu.get(f.options(num_cpus=1).remote(), timeout=30) == "ok"


def test_direct_call_raises(ray_start_2cpu):
    @ray_tpu.remote
    def f():
        return 1

    with pytest.raises(TypeError, match="remote"):
        f()


def test_resources_infeasible_stays_pending(ray_start_2cpu):
    @ray_tpu.remote(num_cpus=64)
    def f():
        return 1

    ref = f.remote()
    ready, pending = ray_tpu.wait([ref], timeout=0.5)
    assert ready == [] and pending == [ref]


def test_locality_aware_actor_placement(ray_start_cluster):
    """A queued (controller-scheduled) actor creation with a large ref arg
    lands on the node holding the argument (pick_node locality preference;
    reference dependency_manager.h + hybrid policy locality)."""
    import numpy as np

    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2, resources={"side": 1})
    ray_tpu.init(address=cluster.address)

    @ray_tpu.remote(resources={"side": 1})
    def make_big():
        return np.zeros(2 * 1024 * 1024, dtype=np.uint8)  # holder: side node

    big_ref = make_big.remote()
    ray_tpu.wait([big_ref], num_returns=1, timeout=60)

    @ray_tpu.remote
    class Holder:
        def __init__(self, arr):
            self.n = int(arr.nbytes)

        def where(self):
            import os

            return os.environ.get("RT_NODE_ID")

    h = Holder.remote(big_ref)
    node = ray_tpu.get(h.where.remote(), timeout=120)
    assert node == cluster.nodes[0].node_id, (
        "actor should be placed on the node holding its 2MB argument")
