"""OOM defense: memory monitor + worker killing policy.

Parity target: reference python/ray/tests/test_memory_pressure.py — a task
that pushes node memory past the threshold is killed by the monitor and the
owner sees OutOfMemoryError (memory_monitor.h, worker_killing_policy.h).
"""

import pytest

import ray_tpu
from ray_tpu import exceptions


def _used_fraction() -> float:
    total = avail = None
    with open("/proc/meminfo") as f:
        for line in f:
            if line.startswith("MemTotal:"):
                total = int(line.split()[1])
            elif line.startswith("MemAvailable:"):
                avail = int(line.split()[1])
    return 1.0 - avail / total


def _stable_used_fraction(window: float = 0.005,
                          timeout: float = 30.0) -> tuple:
    """Baseline for threshold tests: host memory DECAYS for a while after
    heavy suites (freed allocations / page cache settling), and a baseline
    measured high makes the hog miss the threshold once usage drops. Wait
    for two agreeing readings, then keep the MINIMUM seen — usage only
    falls between tests, so the floor is the honest baseline. Returns
    (baseline, settled): settled=False means the host never produced two
    agreeing readings — prior-suite residue is still draining and any
    threshold derived now would be a guess (callers skip)."""
    import time

    deadline = time.monotonic() + timeout
    prev = _used_fraction()
    low = prev
    while time.monotonic() < deadline:
        # 3s between readings: a slowly-decaying curve can show two
        # agreeing readings over a shorter gap while still draining.
        time.sleep(3.0)
        cur = _used_fraction()
        low = min(low, cur)
        if abs(cur - prev) < window:
            return low, True
        prev = cur
    return low, False


def _oom_baseline_or_skip() -> float:
    """Gate flaky preconditions BEFORE init: mid-suite, host memory can
    keep decaying past the measurement window (observed: the retriable
    test failing mid-suite but passing in isolation). An unsettled or
    already-pressured host gets a skip, not a flaky failure."""
    base, settled = _stable_used_fraction()
    if not settled:
        pytest.skip("host memory not settled (prior-suite residue still "
                    "draining); OOM threshold would be a guess")
    if base > 0.85:
        pytest.skip("host already under memory pressure")
    # The hog caps itself at 12 GiB (crashing the host outright is worse
    # than skipping): on hosts so large that threshold-crossing needs more
    # than the cap, the monitor could never fire — skip, don't flake.
    total = None
    with open("/proc/meminfo") as f:
        for line in f:
            if line.startswith("MemTotal:"):
                total = int(line.split()[1]) * 1024
                break
    if total and 0.06 * total > 12 * 1024**3:
        pytest.skip("host too large to safely cross the OOM threshold "
                    "with a bounded hog")
    return base


def _make_hog(threshold: float, max_retries: int):
    @ray_tpu.remote(max_retries=max_retries)
    def hog():
        import numpy as np

        # Size the allocation from the LIVE meminfo reading, not the
        # driver's baseline: if host usage decayed after the threshold was
        # chosen, a fixed 6 GiB could land short of it and the monitor
        # would never fire (the mid-suite flake). Touched ones, not zeros:
        # lazily-mapped zero pages never become resident and never move
        # MemAvailable.
        total = avail = None
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1]) * 1024
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1]) * 1024
        used_frac = 1.0 - avail / total
        need = int((threshold - used_frac + 0.04) * total)
        need = max(1 * 1024**3, min(need, 12 * 1024**3))
        data = np.ones(need, dtype=np.uint8)
        import time

        time.sleep(60)
        return int(data[0])

    return hog


def test_oom_killed_task_raises_oom_error(shutdown_only):
    base = _oom_baseline_or_skip()
    # Threshold sits just above current usage; the hog task crosses it.
    threshold = min(0.95, base + 0.02)
    ray_tpu.init(num_cpus=2, _system_config={
        "memory_usage_threshold": threshold,
        "memory_monitor_refresh_ms": 100,
    })
    hog = _make_hog(threshold, max_retries=0)
    with pytest.raises(exceptions.OutOfMemoryError):
        ray_tpu.get(hog.remote(), timeout=120)


def test_oom_retriable_task_retries_then_fails(shutdown_only):
    base = _oom_baseline_or_skip()
    threshold = min(0.95, base + 0.02)
    ray_tpu.init(num_cpus=2, _system_config={
        "memory_usage_threshold": threshold,
        "memory_monitor_refresh_ms": 100,
    })
    hog = _make_hog(threshold, max_retries=1)
    # Both the first attempt and the retry get OOM-killed; the final error
    # is still OutOfMemoryError (retry accounting must survive the kill).
    with pytest.raises(exceptions.OutOfMemoryError):
        ray_tpu.get(hog.remote(), timeout=240)
