"""OOM defense: memory monitor + worker killing policy.

Parity target: reference python/ray/tests/test_memory_pressure.py — a task
that pushes node memory past the threshold is killed by the monitor and the
owner sees OutOfMemoryError (memory_monitor.h, worker_killing_policy.h).
"""

import pytest

import ray_tpu
from ray_tpu import exceptions


def _used_fraction() -> float:
    total = avail = None
    with open("/proc/meminfo") as f:
        for line in f:
            if line.startswith("MemTotal:"):
                total = int(line.split()[1])
            elif line.startswith("MemAvailable:"):
                avail = int(line.split()[1])
    return 1.0 - avail / total


def _stable_used_fraction(window: float = 0.005, timeout: float = 30.0) -> float:
    """Baseline for threshold tests: host memory DECAYS for a while after
    heavy suites (freed allocations / page cache settling), and a baseline
    measured high makes the hog miss the threshold once usage drops. Wait
    for two agreeing readings, then keep the MINIMUM seen — usage only
    falls between tests, so the floor is the honest baseline."""
    import time

    deadline = time.monotonic() + timeout
    prev = _used_fraction()
    low = prev
    while time.monotonic() < deadline:
        time.sleep(3.0)
        cur = _used_fraction()
        low = min(low, cur)
        if abs(cur - prev) < window:
            return low
        prev = cur
    return low


def test_oom_killed_task_raises_oom_error(shutdown_only):
    base = _stable_used_fraction()
    if base > 0.85:
        pytest.skip("host already under memory pressure")
    # Threshold sits just above current usage; the hog task crosses it.
    ray_tpu.init(num_cpus=2, _system_config={
        "memory_usage_threshold": min(0.95, base + 0.02),
        "memory_monitor_refresh_ms": 100,
    })

    @ray_tpu.remote(max_retries=0)
    def hog():
        import numpy as np

        # ~6 GiB touched (ones, not zeros: lazily-mapped zero pages would
        # never become resident and never move MemAvailable).
        data = np.ones(6 * 1024**3, dtype=np.uint8)
        import time

        time.sleep(60)
        return int(data[0])

    with pytest.raises(exceptions.OutOfMemoryError):
        ray_tpu.get(hog.remote(), timeout=120)


def test_oom_retriable_task_retries_then_fails(shutdown_only):
    base = _stable_used_fraction()
    if base > 0.85:
        pytest.skip("host already under memory pressure")
    ray_tpu.init(num_cpus=2, _system_config={
        "memory_usage_threshold": min(0.95, base + 0.02),
        "memory_monitor_refresh_ms": 100,
    })

    @ray_tpu.remote(max_retries=1)
    def hog():
        import numpy as np

        data = np.ones(6 * 1024**3, dtype=np.uint8)
        import time

        time.sleep(60)
        return int(data[0])

    # Both the first attempt and the retry get OOM-killed; the final error
    # is still OutOfMemoryError (retry accounting must survive the kill).
    with pytest.raises(exceptions.OutOfMemoryError):
        ray_tpu.get(hog.remote(), timeout=240)
