"""Storage plane: URI parsing, backend semantics (atomic put, rename
commit, listdir), the pluggable registry, and sim:// fault injection.

These are the contracts the checkpoint engine's manifest-last protocol
builds on (README "Checkpointing & storage").
"""

import os
import threading

import pytest

from ray_tpu import storage
from ray_tpu.storage import (
    StorageError,
    StorageNotFoundError,
    StorageTransientError,
)
from ray_tpu.storage.mem import MemBackend
from ray_tpu.storage.sim import faults


@pytest.fixture(autouse=True)
def _clean_sim_and_mem():
    faults().clear()
    MemBackend.clear_all()
    yield
    faults().clear()
    MemBackend.clear_all()


# ------------------------------------------------------------- uri parsing
def test_parse_uri_schemes():
    assert storage.parse_uri("/a/b") == ("local", "/a/b")
    assert storage.parse_uri("local:///a/b") == ("local", "/a/b")
    assert storage.parse_uri("file:///a/b") == ("local", "/a/b")
    assert storage.parse_uri("sim:///a/b") == ("sim", "/a/b")
    assert storage.parse_uri("mem://bucket/k") == ("mem", "bucket/k")


def test_join_keeps_bare_paths_bare():
    assert storage.join("/a", "b", "c") == "/a/b/c"
    assert storage.join("mem://x", "y") == "mem://x/y"
    assert storage.join("sim:///a/", "/b") == "sim:///a/b"


def test_is_local_and_local_path():
    assert storage.is_local("/a/b") and storage.local_path("/a/b") == "/a/b"
    assert storage.is_local("local:///a") and storage.local_path("local:///a") == "/a"
    # sim is fs-backed but must NOT be treated as local: direct fs access
    # would bypass fault injection.
    assert not storage.is_local("sim:///a")
    assert storage.local_path("mem://b/k") is None


def test_unknown_scheme_and_registration():
    with pytest.raises(StorageError):
        storage.get_backend("gs://bucket/x")
    storage.register_backend("gs", MemBackend)
    try:
        be, path = storage.get_backend("gs://bucket/x")
        assert isinstance(be, MemBackend) and path == "bucket/x"
    finally:
        storage.backend._REGISTRY.pop("gs", None)
        storage.backend._INSTANCES.pop("gs", None)


# --------------------------------------------------------------- backends
@pytest.fixture(params=["local", "mem", "sim"])
def root(request, tmp_path):
    if request.param == "local":
        return str(tmp_path / "store")
    if request.param == "sim":
        return "sim://" + str(tmp_path / "simstore")
    return "mem://test-root"


def test_backend_put_get_list_delete_rename(root):
    a = storage.join(root, "dir", "a.bin")
    storage.put(a, b"hello")
    assert storage.exists(a)
    assert storage.get_bytes(a) == b"hello"
    assert storage.size(a) == 5
    # streamed parts
    b = storage.join(root, "dir", "b.bin")
    storage.put(b, [b"he", bytearray(b"l"), memoryview(b"lo")])
    assert storage.get_bytes(b) == b"hello"
    assert sorted(storage.listdir(storage.join(root, "dir"))) == ["a.bin", "b.bin"]
    # rename is the commit primitive
    c = storage.join(root, "dir", "MANIFEST.json")
    storage.rename(b, c)
    assert not storage.exists(b) and storage.get_bytes(c) == b"hello"
    assert storage.delete(a) is True
    assert storage.delete(a) is False
    storage.delete_prefix(storage.join(root, "dir"))
    assert storage.listdir(storage.join(root, "dir")) == []


def test_backend_get_missing_raises(root):
    with pytest.raises(StorageNotFoundError):
        storage.get_bytes(storage.join(root, "nope.bin"))
    with pytest.raises(StorageNotFoundError):
        storage.size(storage.join(root, "nope.bin"))


def test_backend_put_overwrite_atomic(root):
    p = storage.join(root, "x.bin")
    storage.put(p, b"one")
    storage.put(p, b"two")
    assert storage.get_bytes(p) == b"two"


def test_local_put_is_atomic_no_partial_visible(tmp_path):
    """A concurrent reader either sees the full old or full new object —
    never a torn write (tmp + os.replace)."""
    p = str(tmp_path / "obj.bin")
    storage.put(p, b"A" * 1_000_000)
    stop = threading.Event()
    bad = []

    def reader():
        while not stop.is_set():
            data = storage.get_bytes(p)
            if len(data) != 1_000_000 or data[0:1] not in (b"A", b"B"):
                bad.append(len(data))
            if data[0:1] == b"B" and data[-1:] != b"B":
                bad.append("torn")

    t = threading.Thread(target=reader)
    t.start()
    for _ in range(20):
        storage.put(p, b"B" * 1_000_000)
        storage.put(p, b"A" * 1_000_000)
    stop.set()
    t.join()
    assert not bad, bad


def test_mem_rename_prefix():
    storage.put("mem://r/src/a", b"1")
    storage.put("mem://r/src/sub/b", b"2")
    storage.rename("mem://r/src", "mem://r/dst")
    assert storage.get_bytes("mem://r/dst/a") == b"1"
    assert storage.get_bytes("mem://r/dst/sub/b") == b"2"
    assert storage.listdir("mem://r/src") == []


# ------------------------------------------------------------ sim chaos
def test_sim_injected_transient_failure(tmp_path):
    root = "sim://" + str(tmp_path / "s")
    faults().add_rule(op="put", after=1, times=1)
    storage.put(storage.join(root, "ok.bin"), b"x")  # admitted (after=1)
    with pytest.raises(StorageTransientError):
        storage.put(storage.join(root, "fail.bin"), b"x")
    # schedule exhausted (times=1): next put goes through
    storage.put(storage.join(root, "ok2.bin"), b"x")
    assert faults().stats.get("put") == 1


def test_sim_fatal_failure(tmp_path):
    root = "sim://" + str(tmp_path / "s")
    faults().add_rule(op="put", error="fatal", times=1)
    with pytest.raises(StorageError) as ei:
        storage.put(storage.join(root, "f.bin"), b"x")
    assert not isinstance(ei.value, StorageTransientError)


def test_sim_sever_and_restore(tmp_path):
    root = "sim://" + str(tmp_path / "s")
    storage.put(storage.join(root, "a.bin"), b"x")
    faults().sever()
    with pytest.raises(StorageTransientError):
        storage.get_bytes(storage.join(root, "a.bin"))
    with pytest.raises(StorageTransientError):
        storage.put(storage.join(root, "b.bin"), b"x")
    faults().restore()
    assert storage.get_bytes(storage.join(root, "a.bin")) == b"x"


def test_sim_latency_knob(tmp_path, monkeypatch):
    import time

    # Through _system_config, not env: once a cluster has started in this
    # process, the propagated config snapshot shadows env overrides.
    from ray_tpu._private.rtconfig import CONFIG

    monkeypatch.setitem(CONFIG._overrides, "sim_storage_latency_s", 0.05)
    root = "sim://" + str(tmp_path / "s")
    t0 = time.perf_counter()
    storage.put(storage.join(root, "a.bin"), b"x")
    assert time.perf_counter() - t0 >= 0.05


def test_sim_is_fs_backed_for_forensics(tmp_path):
    """Objects written via sim:// land on the real fs — a process killed
    mid-save leaves partial files that GC tests can find."""
    root = str(tmp_path / "s")
    storage.put("sim://" + os.path.join(root, "a.bin"), b"x")
    assert os.path.exists(os.path.join(root, "a.bin"))
