"""Telemetry & profiling plane (README "Telemetry & profiling").

Covers: sampling-off is byte-identical inert (no sampler thread anywhere,
no heartbeat telemetry key, empty controller ring); armed sampling yields
multi-kind monotone timeseries + cluster_utilization; controller
self-metrics (per-RPC-method latency histograms, table-size gauges) in
get_metrics and the Prometheus exposition; exposition correctness (+Inf
cumulative == _count for empty AND non-empty overflow buckets, one
HELP/TYPE per family, label escaping round-trip); uniform list-API
truncation markers; on-demand CPU profiling of a live worker end to end
(capture -> storage persist -> registry -> /api/profiles fetch); and the
`ray-tpu top` renderer.

reference: dashboard/modules/reporter/ (reporter agent + metrics head) and
util/state list APIs.
"""

import json
import re
import time

import pytest

import ray_tpu
from ray_tpu.util import state


@pytest.fixture
def telemetry_cluster(monkeypatch, shutdown_only):
    """Cluster with the sampling plane armed at a fast cadence (workers
    inherit the env through the agent spawn path)."""
    monkeypatch.setenv("RT_TELEMETRY_INTERVAL_S", "0.2")
    ray_tpu.init(num_cpus=2)
    yield


def test_telemetry_off_is_inert(ray_start_2cpu):
    """RT_TELEMETRY unset: no sampler thread in any worker, no agent
    sample ring, no controller self-sample task, no series ever ingested —
    the heartbeat wire shape is unchanged (the `telemetry` key is only
    attached when the agent ring exists and is non-empty)."""
    assert "RT_TELEMETRY_INTERVAL_S" not in __import__("os").environ

    @ray_tpu.remote
    def thread_names():
        import threading

        return sorted(t.name for t in threading.enumerate())

    names = ray_tpu.get(thread_names.remote(), timeout=60)
    assert not any("rt-telemetry" in n for n in names), names
    head = ray_tpu._head
    assert head.agent._telem_pending is None
    assert head.controller._telem_task is None
    time.sleep(3 * 0.5)  # several heartbeats
    assert head.controller.telemetry == {}


def test_timeseries_kinds_and_monotone_timestamps(telemetry_cluster):
    @ray_tpu.remote
    def work(i):
        time.sleep(0.05)
        return i

    ray_tpu.get([work.remote(i) for i in range(4)], timeout=60)
    deadline = time.monotonic() + 20
    kinds = set()
    while time.monotonic() < deadline:
        rows = state.timeseries()
        kinds = {r["series"] for r in rows}
        if {"node.cpu", "node.rss", "worker.cpu"} <= kinds:
            break
        time.sleep(0.3)
    # >= 3 distinct series kinds across node / worker / controller scopes
    assert {"node.cpu", "node.rss", "worker.cpu"} <= kinds, kinds
    assert any(k.startswith("ctrl.") for k in kinds), kinds
    for r in state.timeseries():
        ts = [p[0] for p in r["points"]]
        assert ts == sorted(ts) and len(ts) == len(set(ts)), (
            f"non-monotone timestamps in {r['series']}: {ts}")
    # filters: exact series, family prefix, node scoping
    only_cpu = state.timeseries(series="node.cpu")
    assert only_cpu and all(r["series"] == "node.cpu" for r in only_cpu)
    fam = state.timeseries(series="node.")
    assert {r["series"] for r in fam} >= {"node.cpu", "node.rss"}
    nid = only_cpu[0]["node_id"]
    assert all(r["node_id"] == nid
               for r in state.timeseries(node_id=nid))
    assert state.timeseries(node_id="nonexistent") == []
    # since= returns only strictly newer points
    last = only_cpu[0]["points"][-1][0]
    newer = state.timeseries(series="node.cpu", node_id=nid)
    cut = [p for r in newer for p in r["points"] if p[0] <= last]
    fresh = state.timeseries(series="node.cpu", node_id=nid, since=last)
    assert all(p[0] > last for r in fresh for p in r["points"])
    assert cut  # sanity: the cutoff actually removed something


def test_llm_tokens_per_s_series(telemetry_cluster):
    """Engine-hosting workers export live decode throughput as the
    dot-qualified `llm.tokens_per_s` series (README "Serving hot loop"):
    the worker sampler reads the per-tick token rate and the controller
    ingests the dotted key as-is instead of prefixing `worker.`."""
    @ray_tpu.remote
    class EngineHost:
        def tick(self):
            # The real engine counts via _deliver; the counter is the
            # series' source either way (module presence gates sampling).
            from ray_tpu.llm import engine as eng

            eng._count_tokens(1000)
            return True

    h = EngineHost.remote()
    deadline = time.monotonic() + 25
    rows = []
    while time.monotonic() < deadline:
        ray_tpu.get(h.tick.remote(), timeout=30)
        rows = state.timeseries(series="llm.tokens_per_s")
        if rows and any(p[1] > 0 for r in rows for p in r["points"]):
            break
        time.sleep(0.2)
    assert rows, "llm.tokens_per_s series never appeared"
    assert any(p[1] > 0 for r in rows for p in r["points"]), rows
    # Dot-qualified: never double-prefixed into worker.llm.tokens_per_s.
    assert not state.timeseries(series="worker.llm.tokens_per_s")
    # cluster_utilization keeps the qualified key — `ray-tpu top`'s TOK/S
    # column reads workers[wid]["llm.tokens_per_s"] verbatim.
    util = state.cluster_utilization()
    worker_series = [w for n in util["nodes"].values()
                     for w in (n.get("workers") or {}).values()]
    assert any("llm.tokens_per_s" in w for w in worker_series), util
    from ray_tpu.scripts.cli import _top_lines

    frame = "\n".join(_top_lines(util))
    assert "TOK/S" in frame


def test_cluster_utilization_shape(telemetry_cluster):
    @ray_tpu.remote
    def one():
        return 1

    ray_tpu.get([one.remote() for _ in range(3)], timeout=60)
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        u = state.cluster_utilization()
        nodes = u["nodes"]
        if nodes and all("cpu" in n["node"] for n in nodes.values()):
            break
        time.sleep(0.3)
    assert u["telemetry_armed"]
    node = next(iter(nodes.values()))
    assert node["alive"] and {"cpu", "mem", "rss"} <= set(node["node"])
    ctrl = u["controller"]
    assert ctrl["loop_lag_s"] is not None
    assert ctrl["tables"]["nodes"] == 1
    assert ctrl["rpc_total"] > 0


def test_controller_self_metrics(ray_start_2cpu):
    """Per-RPC-method latency histograms + table-size gauges need NO
    telemetry arming — they accumulate inline and synthesize at scrape."""
    @ray_tpu.remote
    def one():
        return 1

    ray_tpu.get(one.remote(), timeout=60)
    metrics = state.metrics()
    rpc_rows = [m for m in metrics
                if m["name"] == "rt_controller_rpc_seconds"]
    assert rpc_rows, "per-RPC histograms missing from get_metrics"
    methods = {m["tags"]["method"] for m in rpc_rows}
    assert "register" in methods, methods
    for m in rpc_rows:
        assert m["kind"] == "histogram"
        assert sum(m["buckets"]) == m["count"]
        assert len(m["buckets"]) == len(m["boundaries"]) + 1
    tables = {m["tags"]["table"]: m["value"] for m in metrics
              if m["name"] == "rt_controller_table_size"}
    assert {"objects", "actors", "leases", "parked_grants"} <= set(tables)
    assert tables["nodes"] == 1


_PROM_SERIES = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(?P<labels>.*)\})? '
    r'(?P<value>[^ ]+)$')


def _parse_prom(text: str):
    """Minimal Prometheus text parser: returns (types, helps, samples)
    where samples is a list of (name, {label: value}, float)."""
    types: dict[str, list] = {}
    helps: dict[str, list] = {}
    samples = []
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            types.setdefault(name, []).append(kind)
            continue
        if line.startswith("# HELP "):
            _, _, name, desc = line.split(" ", 3)
            helps.setdefault(name, []).append(desc)
            continue
        m = _PROM_SERIES.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        labels = {}
        raw = m.group("labels")
        if raw:
            for lm in re.finditer(r'(\w+)="((?:[^"\\]|\\.)*)"', raw):
                val = (lm.group(2).replace("\\n", "\n")
                       .replace('\\"', '"').replace("\\\\", "\\"))
                labels[lm.group(1)] = val
        samples.append((m.group("name"), labels, float(m.group("value"))))
    return types, helps, samples


def test_prometheus_exposition_correctness():
    """Scrape-and-parse pin (no cluster needed — render_prometheus is the
    single exposition implementation): +Inf cumulative bucket == _count
    for a histogram whose overflow bucket is EMPTY and one whose overflow
    is NON-EMPTY; TYPE/HELP exactly once per family even when tag sets
    differ and only a later series carries the description; label values
    with quotes/backslashes/newlines round-trip."""
    from ray_tpu.dashboard import render_prometheus

    weird = 'a"b\\c\nd'
    metrics = [
        # family h: two tag sets; desc only on the SECOND series
        {"name": "h", "kind": "histogram", "desc": "",
         "tags": {"m": "x"}, "value": 0.0, "count": 6, "sum": 1.5,
         "boundaries": [0.1, 1.0], "buckets": [2, 4, 0]},   # empty +Inf
        {"name": "h", "kind": "histogram", "desc": "h help",
         "tags": {"m": "y"}, "value": 0.0, "count": 7, "sum": 9.0,
         "boundaries": [0.1, 1.0], "buckets": [1, 2, 4]},   # non-empty
        {"name": "g", "kind": "gauge", "desc": "g help",
         "tags": {"lbl": weird}, "value": 4.25,
         "count": 0, "sum": 0.0, "buckets": None},
        # degraded histogram (decl lost): one +Inf bucket only
        {"name": "d", "kind": "histogram", "desc": "",
         "tags": {}, "value": 0.0, "count": 3, "sum": 0.3,
         "boundaries": [], "buckets": [3]},
    ]
    text = render_prometheus(metrics)
    types, helps, samples = _parse_prom(text)
    assert types["h"] == ["histogram"], "TYPE must appear exactly once"
    assert types["g"] == ["gauge"]
    assert types["d"] == ["histogram"]
    assert helps["h"] == ["h help"], "HELP from the series that carries it"
    for tag, count in (("x", 6), ("y", 7)):
        rows = [s for s in samples
                if s[0] == "h_bucket" and s[1].get("m") == tag]
        infs = [v for _, lbl, v in rows if lbl["le"] == "+Inf"]
        assert infs == [float(count)], (
            f"+Inf bucket must equal _count for m={tag}: {rows}")
        # cumulative: non-decreasing in boundary order
        vals = [v for _, _, v in rows]
        assert vals == sorted(vals)
        cnt = [v for n, lbl, v in samples
               if n == "h_count" and lbl.get("m") == tag]
        assert cnt == [float(count)]
    d_inf = [v for n, lbl, v in samples
             if n == "d_bucket" and lbl["le"] == "+Inf"]
    assert d_inf == [3.0]
    g = [s for s in samples if s[0] == "g"]
    assert g and g[0][1]["lbl"] == weird, "label escaping must round-trip"
    assert g[0][2] == 4.25


def test_list_api_truncation_markers(ray_start_2cpu):
    @ray_tpu.remote
    def t(i):
        return i

    ray_tpu.get([t.remote(i) for i in range(4)], timeout=60)
    refs = [ray_tpu.put(b"x" * (1 << 20)) for _ in range(3)]
    time.sleep(0.5)  # event batches drain

    full = state.list_tasks()
    assert full.truncated is False
    clipped = state.list_tasks(limit=2)
    assert clipped.truncated is True and len(clipped) == 2
    objs = state.list_objects(limit=1)
    assert objs.truncated is True and len(objs) == 1
    assert state.list_objects().truncated is False
    assert state.list_traces().truncated is False
    profs = state.list_profiles()
    assert profs == [] and profs.truncated is False
    del refs


def test_profile_worker_cpu_end_to_end(ray_start_2cpu):
    """`profile_worker` on a busy worker: non-empty collapsed stacks
    naming the hot method, persisted under <session>/profiles/, listed in
    the registry, and fetchable through /api/profiles."""
    import os
    import urllib.request

    @ray_tpu.remote
    class Busy:
        def spin(self, seconds):
            t0 = time.time()
            x = 0
            while time.time() - t0 < seconds:
                x += 1
            return x

    a = Busy.remote()
    ref = a.spin.remote(8.0)
    time.sleep(0.5)  # the call is executing
    w = ray_tpu._private.worker.global_worker()
    info = w.io.run(w.controller.call(
        "get_actor_info", actor_id=a._actor_id, wait=True))
    rep = w.io.run(w.controller.call(
        "profile_worker", worker_id=info["worker_id"], seconds=1.0,
        mode="cpu"), timeout=45)
    assert rep.get("found"), rep
    meta = rep["profile"]
    assert meta["samples"] > 10, meta
    assert "/profiles/" in meta["path"]
    assert os.path.exists(meta["path"]), meta["path"]

    rows = state.list_profiles()
    assert any(r["name"] == meta["name"] for r in rows)

    doc = w.io.run(w.controller.call("get_profile", name=meta["name"]),
                   timeout=30)
    assert doc["found"]
    collapsed = doc["collapsed"]
    assert collapsed, "collapsed stacks empty"
    assert any("spin" in stack for stack in collapsed), list(collapsed)[:3]
    assert doc["traceEvents"], "chrome-trace events missing"
    assert any(ev.get("ph") == "X" and "spin" in ev.get("name", "")
               for ev in doc["traceEvents"])

    # prefix fetch + dashboard surface
    from ray_tpu.dashboard import start_dashboard

    d = start_dashboard(port=0)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{d.port}/api/profiles", timeout=10) as r:
            listing = json.loads(r.read())
        assert any(p["name"] == meta["name"] for p in listing["profiles"])
        with urllib.request.urlopen(
                f"http://127.0.0.1:{d.port}/api/profiles?"
                f"name={meta['name'][:10]}", timeout=10) as r:
            fetched = json.loads(r.read())
        assert fetched["found"] and fetched["collapsed"]
    finally:
        d.stop()
    assert ray_tpu.get(ref, timeout=60) > 0


def test_profile_unknown_worker_is_attributed(ray_start_2cpu):
    w = ray_tpu._private.worker.global_worker()
    rep = w.io.run(w.controller.call(
        "profile_worker", worker_id="deadbeef" * 4, seconds=0.2), timeout=30)
    assert rep["found"] is False
    assert "not found" in rep["error"]


def test_top_once_renders(telemetry_cluster, capsys):
    @ray_tpu.remote
    def one():
        return 1

    ray_tpu.get([one.remote() for _ in range(3)], timeout=60)
    # wait for at least one sample to land
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if any(r["series"] == "node.cpu" for r in state.timeseries()):
            break
        time.sleep(0.3)
    w = ray_tpu._private.worker.global_worker()
    addr = f"{w.controller_addr[0]}:{w.controller_addr[1]}"
    from ray_tpu.scripts.cli import main as cli_main

    assert cli_main(["top", "--once", "--address", addr]) == 0
    out = capsys.readouterr().out
    assert "NODE" in out and "CPU%" in out and "HBM" in out
    assert "ALIVE" in out, out
    assert "controller:" in out and "loop_lag" in out
    assert "telemetry idle" not in out


def test_timeseries_api_via_dashboard(telemetry_cluster):
    import urllib.request

    @ray_tpu.remote
    def one():
        return 1

    ray_tpu.get(one.remote(), timeout=60)
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if any(r["series"] == "node.cpu" for r in state.timeseries()):
            break
        time.sleep(0.3)
    from ray_tpu.dashboard import start_dashboard

    d = start_dashboard(port=0)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{d.port}/api/timeseries?series=node.",
                timeout=10) as r:
            rep = json.loads(r.read())
        kinds = {row["series"] for row in rep["series"]}
        assert {"node.cpu", "node.mem", "node.rss"} <= kinds, kinds
        for row in rep["series"]:
            ts = [p[0] for p in row["points"]]
            assert ts == sorted(ts)
        # Prometheus exposition carries the telemetry-era self-metrics too
        with urllib.request.urlopen(
                f"http://127.0.0.1:{d.port}/metrics", timeout=10) as r:
            prom = r.read().decode()
        assert "rt_controller_rpc_seconds_bucket" in prom
        assert 'rt_controller_table_size{table="objects"}' in prom
        assert prom.count("# TYPE rt_controller_rpc_seconds histogram") == 1
    finally:
        d.stop()
