"""Distributed tracing plane: causal spans from submit to decode.

Covers the README "Tracing & timeline" contract: byte-identical off
(RT_TRACING unset changes no wire arity, writes no contextvar, arms no
hook), causal parent/child linkage across nested task submits, trace
continuity across the direct->controller lease failover (exactly one
execute span per attempt) and @remote(timeout_s=) retries (attempts chain
under one trace), the `ray-tpu timeline` Perfetto/catapult export shape,
and the serve acceptance criterion: a traced streaming request's spans
account for >= 90% of end-to-end wall time with per-decode-iteration
host-sync spans individually visible.

reference tests: python/ray/tests/test_tracing.py (trace context
propagation through tasks/actors) + test_state_api timeline coverage.
"""

import json
import os
import subprocess
import sys
import tempfile
import time

import pytest

import ray_tpu
from ray_tpu._private import rpc


def _wait(pred, timeout: float, what: str):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            last = pred()
            if last:
                return last
        except Exception:
            pass
        time.sleep(0.2)
    raise TimeoutError(f"timed out waiting for {what}")


def _all_spans():
    from ray_tpu.util import state

    spans = []
    for row in state.list_traces(limit=100_000):
        spans.extend(state.get_trace(row["trace_id"])["spans"])
    return spans


# ------------------------------------------------------------ off = free
def test_tracing_off_is_byte_identical(shutdown_only):
    """RT_TRACING unset: no hook, no context, and every wire format keeps
    its pre-tracing arity (old peers/snapshots decode new bytes and vice
    versa)."""
    assert not os.environ.get("RT_TRACING")
    ray_tpu.init(num_cpus=1)
    from ray_tpu._private import tracing
    from ray_tpu._private.task_spec import TaskSpec

    assert tracing.enabled() is False
    assert rpc._TRACE is None  # frame hook disarmed

    @ray_tpu.remote
    def f():
        return 1

    assert ray_tpu.get(f.remote(), timeout=60) == 1
    assert tracing.current() is None  # no contextvar writes happened

    spec = TaskSpec(task_id="ab" * 8, kind="normal", name="x",
                    function_id="fn:1")
    assert spec.trace is None
    assert len(spec.__getstate__()) == 26   # pre-tracing state arity
    assert len(spec.task_call_tuple()) == 11
    acall = TaskSpec.for_actor_call("ab" * 8, "m", [], {}, 1, "x",
                                    "o" * 32, None, "a" * 32)
    assert len(acall.actor_call_tuple()) == 7
    import pickle

    rt = pickle.loads(pickle.dumps(spec))
    assert rt.trace is None and rt.task_id == spec.task_id

    from ray_tpu.util import state

    assert state.list_traces() == []  # nothing was recorded anywhere


def test_traced_wire_tuples_round_trip():
    """Sampled specs grow the wire tuples by one trailing trace field;
    both arities decode (back-compat branches)."""
    from ray_tpu._private.task_spec import TaskSpec, actor_call_spec

    tr = ("t" * 32, "s" * 16)
    spec = TaskSpec(task_id="ab" * 8, kind="normal", name="x",
                    function_id="fn:1", trace=tr)
    assert len(spec.__getstate__()) == 27
    call = spec.task_call_tuple()
    assert len(call) == 12
    back = TaskSpec.for_normal_call(call, "o" * 32, None, {})
    assert back.trace == tr
    # Traceless (old-arity) records still decode.
    spec.trace = None
    back2 = TaskSpec.for_normal_call(spec.task_call_tuple(), "o" * 32,
                                     None, {})
    assert back2.trace is None
    spec.trace = tr
    a = TaskSpec.for_actor_call("ab" * 8, "m", [], {}, 1, "x", "o" * 32,
                                None, "a" * 32, trace=tr)
    acall = a.actor_call_tuple()
    assert len(acall) == 8
    assert actor_call_spec(acall, "o" * 32, None, "a" * 32).trace == tr
    assert actor_call_spec(acall[:7], "o" * 32, None, "a" * 32).trace is None


# -------------------------------------------------------- causal linkage
def test_nested_submit_spans_chain_causally(monkeypatch, shutdown_only):
    """driver submit -> parent execute -> child submit -> child execute all
    share one trace_id with correct parentage; dispatch/result spans land."""
    monkeypatch.setenv("RT_TRACING", "1")
    ray_tpu.init(num_cpus=2)

    @ray_tpu.remote
    def child_task(x):
        return x + 1

    @ray_tpu.remote
    def parent_task(x):
        return ray_tpu.get(child_task.remote(x), timeout=60) + 1

    assert ray_tpu.get(parent_task.remote(1), timeout=60) == 3

    def _linked():
        spans = _all_spans()
        p_sub = [s for s in spans if s["k"] == "submit"
                 and s["n"] == "parent_task"]
        p_exe = [s for s in spans if s["k"] == "execute"
                 and s["n"] == "parent_task"]
        c_sub = [s for s in spans if s["k"] == "submit"
                 and s["n"] == "child_task"]
        c_exe = [s for s in spans if s["k"] == "execute"
                 and s["n"] == "child_task"]
        if not (p_sub and p_exe and c_sub and c_exe):
            return None
        ps, pe, cs, ce = p_sub[0], p_exe[0], c_sub[0], c_exe[0]
        assert ps["p"] is None, "driver submit is the trace root"
        assert pe["t"] == ps["t"] and pe["p"] == ps["s"]
        # The child's submit happened INSIDE the parent's execute span.
        assert cs["t"] == ps["t"] and cs["p"] == pe["s"]
        assert ce["t"] == ps["t"] and ce["p"] == cs["s"]
        # Dispatch + result spans ride the same trace.
        kinds = {s["k"] for s in spans if s["t"] == ps["t"]}
        assert "dispatch" in kinds and "result" in kinds
        return True

    _wait(_linked, 30, "causally linked nested-task spans")


def test_actor_call_spans(monkeypatch, shutdown_only):
    monkeypatch.setenv("RT_TRACING", "1")
    ray_tpu.init(num_cpus=2)

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.v = 0

        def bump(self):
            self.v += 1
            return self.v

    c = Counter.remote()
    assert ray_tpu.get(c.bump.remote(), timeout=60) == 1

    def _spans():
        spans = _all_spans()
        sub = [s for s in spans if s["k"] == "submit" and s["n"] == "bump"]
        exe = [s for s in spans if s["k"] == "execute" and s["n"] == "bump"]
        if not (sub and exe):
            return None
        assert exe[0]["t"] == sub[0]["t"] and exe[0]["p"] == sub[0]["s"]
        return True

    _wait(_spans, 30, "actor call spans")


# ------------------------------------------------ continuity across retry
def test_timeout_retry_chains_attempts_in_one_trace(monkeypatch,
                                                    shutdown_only,
                                                    tmp_path):
    """@remote(timeout_s=) attempt 0 is killed by its deadline and retried:
    both attempts' execute spans chain under the SAME submit span of the
    same trace — no orphan or duplicate spans."""
    monkeypatch.setenv("RT_TRACING", "1")
    ray_tpu.init(num_cpus=1)
    marker = str(tmp_path / "attempt0")

    @ray_tpu.remote(timeout_s=0.5, max_retries=1)
    def flaky(path):
        import os as _os
        import time as _t

        if not _os.path.exists(path):
            open(path, "w").close()
            _t.sleep(30)  # attempt 0: wedge past the deadline
        return "ok"

    assert ray_tpu.get(flaky.remote(marker), timeout=120) == "ok"

    def _chained():
        spans = _all_spans()
        subs = [s for s in spans if s["k"] == "submit" and s["n"] == "flaky"]
        exes = [s for s in spans if s["k"] == "execute" and s["n"] == "flaky"]
        if len(exes) < 2:
            return None
        assert len(subs) == 1, f"duplicate submit spans: {subs}"
        assert len(exes) == 2, f"expected one execute span per attempt: {exes}"
        sub = subs[0]
        attempts = sorted((e.get("at") or {}).get("attempt") for e in exes)
        assert attempts == [0, 1]
        for e in exes:
            assert e["t"] == sub["t"] and e["p"] == sub["s"]
        oks = {(e.get("at") or {}).get("attempt"):
               (e.get("at") or {}).get("ok") for e in exes}
        assert oks[0] is False and oks[1] is True
        return True

    _wait(_chained, 40, "timeout-retry attempts chained in one trace")


# ------------------------------------------- continuity across failover
def _spawn_agent(controller_addr: str, session: str, num_cpus=2):
    env = dict(os.environ)
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    driver_paths = [p for p in sys.path if p and os.path.exists(p)]
    env["PYTHONPATH"] = os.pathsep.join([pkg_root] + driver_paths)
    from ray_tpu._private.ids import NodeID
    from ray_tpu._private.resources import ResourceSet

    node_id = NodeID.from_random().hex()
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.node_agent",
         "--controller", controller_addr,
         "--node-id", node_id,
         "--session", session,
         "--resources",
         json.dumps(ResourceSet({"CPU": float(num_cpus)}).raw())],
        env=env)
    return node_id, proc


def test_lease_failover_keeps_one_execute_span_per_attempt(monkeypatch):
    """Sever every owner->worker lease connection mid-batch (the PR 6
    failover + dedup-replay path): every ref still resolves, and the trace
    plane shows EXACTLY one execute span per task, chained to that task's
    submit span — the failover re-route neither loses nor duplicates
    spans."""
    monkeypatch.setenv("RT_TRACING", "1")
    procs = []
    try:
        ray_tpu.init(num_cpus=0, _system_config={"fault_injection": True})
        head = ray_tpu._head
        addr = f"{head.controller_addr[0]}:{head.controller_addr[1]}"
        nid, proc = _spawn_agent(addr, head.session_id, num_cpus=2)
        procs.append(proc)

        def _snapshot():
            return ray_tpu._private.worker.global_worker().state_snapshot()

        _wait(lambda: (_snapshot()["nodes"].get(nid) or {}).get("alive"),
              60, "node to register")

        marker_dir = tempfile.mkdtemp(prefix="rt_trace_fo_")
        log = os.path.join(marker_dir, "executions.log")

        @ray_tpu.remote(num_cpus=1, max_retries=0)
        def tracked(i, path):
            import os as _os
            import time as _t

            fd = _os.open(path, _os.O_WRONLY | _os.O_CREAT | _os.O_APPEND,
                          0o644)
            _os.write(fd, f"{i}\n".encode())
            _os.close(fd)
            _t.sleep(0.15)
            return i

        ray_tpu.get([tracked.remote(-1 - j, log) for j in range(2)],
                    timeout=60)
        n = 8
        refs = [tracked.remote(i, log) for i in range(n)]
        task_ids = [r.task_id() for r in refs]

        def _started():
            try:
                with open(log) as f:
                    return sum(1 for ln in f if not ln.startswith("-")) >= 2
            except OSError:
                return False

        _wait(_started, 30, "batch to start executing")
        inj = rpc.fault_injector()
        assert inj.sever("lease") >= 1, "no lease connections to sever"
        assert ray_tpu.get(refs, timeout=120) == list(range(n))

        def _one_exec_each():
            spans = _all_spans()
            by_task: dict = {}
            subs: dict = {}
            for s in spans:
                t = (s.get("at") or {}).get("task")
                if t is None:
                    continue
                if s["k"] == "execute":
                    by_task.setdefault(t, []).append(s)
                elif s["k"] == "submit":
                    subs[t] = s
            if not all(tid in by_task for tid in task_ids):
                return None
            for tid in task_ids:
                exes = by_task[tid]
                assert len(exes) == 1, (
                    f"task {tid[:12]} has {len(exes)} execute spans "
                    f"(failover duplicated or lost the execution)")
                sub = subs.get(tid)
                assert sub is not None
                assert exes[0]["t"] == sub["t"]
                assert exes[0]["p"] == sub["s"]
            return True

        _wait(_one_exec_each, 40,
              "exactly one execute span per task after failover")
    finally:
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        for proc in procs:
            try:
                proc.kill()
            except Exception:
                pass
        inj = rpc.fault_injector()
        if inj is not None:
            inj.clear()
        rpc.disable_fault_injection()


# ---------------------------------------------------- timeline export
def test_timeline_cli_exports_perfetto_json(monkeypatch, shutdown_only,
                                            tmp_path):
    """`ray-tpu timeline -o` emits catapult-shaped JSON Perfetto accepts:
    a traceEvents list of complete "X" events (plus "M" metadata) with
    numeric, monotonically non-decreasing timestamps."""
    monkeypatch.setenv("RT_TRACING", "1")
    ray_tpu.init(num_cpus=1)

    @ray_tpu.remote
    def traced_fn(x):
        return x * 2

    assert ray_tpu.get(traced_fn.remote(21), timeout=60) == 42
    from ray_tpu.util import state

    # Wait for what the export assert below actually needs (>= 3 spans):
    # the worker's execute/result spans ride a LATER metrics-flush tick
    # than the driver's submit span, and exporting after the first span
    # alone made this a load-dependent flake.
    _wait(lambda: any(r["spans"] >= 3 for r in state.list_traces()),
          30, "traces indexed controller-side")

    head = ray_tpu._head
    addr = f"{head.controller_addr[0]}:{head.controller_addr[1]}"
    out = str(tmp_path / "trace.json")
    from ray_tpu.scripts.cli import main as cli_main

    assert cli_main(["timeline", "--address", addr, "-o", out]) == 0
    doc = json.load(open(out))
    evs = doc["traceEvents"]
    assert isinstance(evs, list) and evs
    assert doc.get("displayTimeUnit") == "ms"
    last_ts = -1.0
    seen_x = 0
    for e in evs:
        assert e["ph"] in ("X", "M"), f"unexpected event phase: {e}"
        assert isinstance(e["pid"], int)
        if e["ph"] == "X":
            seen_x += 1
            assert isinstance(e["ts"], (int, float))
            assert isinstance(e["dur"], (int, float)) and e["dur"] >= 1.0
            assert e["ts"] >= last_ts, "timestamps must be monotonic"
            last_ts = e["ts"]
            assert e["name"] and "cat" in e and "tid" in e
    assert seen_x >= 3  # at least submit/dispatch-or-result/execute

    # --trace with a unique prefix selects one trace.
    rows = state.list_traces()
    tid = rows[-1]["trace_id"]
    out2 = str(tmp_path / "one.json")
    assert cli_main(["timeline", "--address", addr, "--trace", tid[:12],
                     "-o", out2]) == 0
    doc2 = json.load(open(out2))
    assert all((e["args"].get("trace_id") == tid)
               for e in doc2["traceEvents"] if e["ph"] == "X")


def test_trace_persisted_through_storage_plane(monkeypatch, shutdown_only):
    """Completed traces land under <session>/traces/ via the PR 8 storage
    backend and stay readable through get_trace after controller eviction
    (simulated by reading the file directly)."""
    monkeypatch.setenv("RT_TRACING", "1")
    ray_tpu.init(num_cpus=1)

    @ray_tpu.remote
    def f():
        return 1

    assert ray_tpu.get(f.remote(), timeout=60) == 1
    from ray_tpu.util import state

    rows = _wait(lambda: [r for r in state.list_traces() if r["complete"]],
                 30, "a completed trace")
    tid = rows[0]["trace_id"]
    head = ray_tpu._head
    from ray_tpu._private.rtconfig import CONFIG

    tdir = os.path.join(CONFIG.session_dir, head.session_id, "traces")
    path = os.path.join(tdir, f"{tid}.json")
    _wait(lambda: os.path.exists(path), 30, "trace persisted to storage")
    doc = json.load(open(path))
    assert doc["trace_id"] == tid and doc["spans"]


# ---------------------------------------------- serve acceptance criterion
def test_serve_streaming_trace_accounts_request_wall_time(monkeypatch,
                                                          shutdown_only):
    """ISSUE 11 acceptance: on a traced serve streaming-generation request,
    the exported spans account for >= 90% of end-to-end request wall time,
    and per-decode-iteration engine.host_sync spans make the host-link
    round trips individually visible."""
    monkeypatch.setenv("RT_TRACING", "1")
    ray_tpu.init(num_cpus=4)
    from ray_tpu import serve
    from ray_tpu.llm import LLMConfig
    from ray_tpu.llm.openai import build_openai_app

    import socket
    import urllib.request

    cfg = LLMConfig(vocab_size=384, d_model=64, n_layers=2, n_heads=4,
                    max_seq=128)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    app = build_openai_app(cfg, model_id="traced-llm", max_batch=4,
                           decode_chunk=4, default_max_tokens=24)
    serve.run(app, route_prefix="/", port=port)
    try:
        body = json.dumps({"prompt": "hello tracer", "max_tokens": 24,
                           "temperature": 0.0, "stream": True}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/completions", data=body,
            headers={"Content-Type": "application/json"})
        # SSE events carry token BATCHES (the token-ring reply path
        # coalesces a decode chunk into one event): count tokens, not
        # events.
        ntok = 0
        with urllib.request.urlopen(req, timeout=180) as r:
            for line in r:
                line = line.decode().strip()
                if line.startswith("data: ") and line != "data: [DONE]":
                    ntok += len(json.loads(line[6:]).get("token_ids", []))
        assert ntok >= 24

        from ray_tpu.util import state

        def _request_trace():
            for row in state.list_traces(limit=1000):
                if not row["complete"]:
                    continue
                if not str(row.get("name") or "").startswith("http POST"):
                    continue
                doc = state.get_trace(row["trace_id"])
                spans = doc["spans"]
                if (any(s["n"] == "engine.host_sync" for s in spans)
                        and any(s["k"] == "execute" for s in spans)
                        and any(s["p"] is None for s in spans)):
                    return doc
            return None

        doc = _wait(_request_trace, 40, "request trace with engine spans")
        spans = doc["spans"]
        root = next(s for s in spans if s["p"] is None)
        wall = root["b"] - root["a"]
        assert wall > 0
        # Union of child-span coverage clipped to the root window.
        ivs = sorted(
            (max(s["a"], root["a"]), min(s["b"], root["b"]))
            for s in spans if s is not root and s["b"] > s["a"])
        covered, cur = 0.0, None
        for a, b in ivs:
            if b <= a:
                continue
            if cur is None:
                cur = [a, b]
            elif a <= cur[1]:
                cur[1] = max(cur[1], b)
            else:
                covered += cur[1] - cur[0]
                cur = [a, b]
        if cur is not None:
            covered += cur[1] - cur[0]
        assert covered >= 0.9 * wall, (
            f"spans cover only {covered / wall:.1%} of the request's "
            f"{wall * 1e3:.0f}ms wall time")
        # Per-decode-iteration host syncs: the BENCH_r05 host-link cost,
        # individually visible (>= 2 iterations for 24 tokens at chunk 4 /
        # depth 4).
        syncs = [s for s in spans if s["n"] == "engine.host_sync"]
        assert len(syncs) >= 2, f"host syncs not per-iteration: {syncs}"
        # ISSUE 13 acceptance: host syncs are bounded by the CHUNK count,
        # never the token count — 24 tokens at decode_chunk=4 is ceil(24/4)
        # = 6 chunks, plus O(1) slack for the first-token readback and the
        # pipeline's tail drains (depth 4). A per-token readback loop
        # would show >= 24.
        import math

        bound = math.ceil(24 / 4) + 4 + 3
        assert len(syncs) <= bound, (
            f"{len(syncs)} host_sync spans for a 24-token/chunk-4 request "
            f"(bound {bound}): the decode loop is syncing per token again")
        assert any(s["n"] == "engine.dispatch_chunk" for s in spans)
        assert any(s["n"] == "engine.prefill" for s in spans)
    finally:
        serve.shutdown()


# ---------------------------------------------------------- stall linkage
def test_stall_report_carries_trace_id(monkeypatch, shutdown_only):
    """A stalled TRACED task's StallReport names its trace id, linking
    `ray-tpu stalls` output to `ray-tpu timeline --trace`."""
    monkeypatch.setenv("RT_TRACING", "1")
    monkeypatch.setenv("RT_STALL_WARN_S", "0.6")
    monkeypatch.setenv("RT_STALL_BEACON_INTERVAL_S", "0.1")
    ray_tpu.init(num_cpus=1)

    @ray_tpu.remote
    def spinner():
        import time as _t

        _t.sleep(2.5)  # no progress reports: crosses the warn threshold
        return "done"

    ref = spinner.remote()
    from ray_tpu.util import state

    def _stall_with_trace():
        rows = [r for r in state.list_stalls()
                if r.get("stage") == "warn" and r.get("trace_id")]
        return rows or None

    rows = _wait(_stall_with_trace, 30, "stall report carrying a trace id")
    assert ray_tpu.get(ref, timeout=60) == "done"
    tid = rows[0]["trace_id"]

    def _trace_known():
        return any(r["trace_id"] == tid for r in state.list_traces())

    _wait(_trace_known, 30, "the stalled task's trace to be indexed")


def test_unsampled_stall_escalates_to_trace_root(monkeypatch, shutdown_only):
    """Always-sample escalation: a stalled task whose root was NOT sampled
    (RT_TRACE_SAMPLE=0) still gets a minted trace root, and the stall
    report names it."""
    monkeypatch.setenv("RT_TRACING", "1")
    monkeypatch.setenv("RT_TRACE_SAMPLE", "0")
    monkeypatch.setenv("RT_STALL_WARN_S", "0.6")
    monkeypatch.setenv("RT_STALL_BEACON_INTERVAL_S", "0.1")
    ray_tpu.init(num_cpus=1)

    @ray_tpu.remote
    def spinner2():
        import time as _t

        _t.sleep(2.5)
        return "done"

    ref = spinner2.remote()
    from ray_tpu.util import state

    rows = _wait(lambda: [r for r in state.list_stalls()
                          if r.get("name") == "spinner2"
                          and r.get("trace_id")],
                 30, "unsampled stall report carrying an escalation trace")
    assert ray_tpu.get(ref, timeout=60) == "done"
    tid = rows[0]["trace_id"]
    doc = _wait(lambda: (state.get_trace(tid)
                         if state.get_trace(tid).get("found") else None),
                30, "the escalation trace root to be indexed")
    roots = [s for s in doc["spans"] if s["p"] is None]
    assert roots and (roots[0].get("at") or {}).get("stalled") is True
    assert (roots[0].get("at") or {}).get("sampled") is False
