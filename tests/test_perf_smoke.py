"""CI perf smoke: `python bench.py --smoke` must complete sub-30s-per-
section and emit its one-line JSON report. Marked `perf` — never runs in
the tier-1 budget; enable with RT_RUN_PERF=1 (e.g. a dedicated perf CI
lane)."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.perf


def test_bench_smoke_runs():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py"), "--smoke"],
        capture_output=True, text=True, timeout=720, env=env, cwd=root)
    assert out.returncode == 0, out.stderr[-2000:]
    rep = json.loads(out.stdout.strip().splitlines()[-1])
    assert rep["metric"] == "microbench_geomean"
    assert rep["details"].get("smoke") is True
    # The hot-path metrics this PR targets must be present and nonzero.
    for k in ("multi_client_tasks_async", "n_n_actor_calls_async",
              "single_client_put_gigabytes"):
        assert rep["details"][k] > 0
    # Direct dispatch must beat the controller path on the SAME
    # multi-client workload (the tentpole's reason to exist). Margin is
    # deliberately modest — this is a smoke guard, not a benchmark.
    direct = rep["details"]["multi_client_tasks_async"]
    ctrl = rep["details"].get("multi_client_tasks_async_controller_path")
    assert ctrl and ctrl > 0, "controller-path comparison missing"
    assert direct > 1.2 * ctrl, (
        f"direct dispatch ({direct}/s) does not beat the controller path "
        f"({ctrl}/s)")
    # Device object plane A/B: actor→actor 64MB jax.Array handoff must
    # beat the host-store path (RT_DEVICE_OBJECTS=0) by a clear margin —
    # the plane skips the producer-side host materialization the host
    # path pays at return time (README "Device objects").
    dev = rep["details"].get("device_object_p2p_gbps")
    host = rep["details"].get("device_object_p2p_host_gbps")
    assert dev is not None and host is not None, (
        "device_object_p2p A/B missing (bench skipped it: see its stderr)")
    assert host > 0, f"host-store path measured {host} GB/s"
    assert dev > 1.5 * host, (
        f"device object plane ({dev} GB/s) does not beat the host store "
        f"path ({host} GB/s) by 1.5x")
    # Checkpoint engine: saves must move real bytes, and async
    # checkpointing must (a) stay off the step path (< 1.2x a
    # checkpoint-free loop) and (b) hide the commit latency a sync save
    # pays on the step (README "Checkpointing & storage").
    assert rep["details"].get("checkpoint_save_gbps", 0) > 0, (
        "checkpoint bench missing (see its stderr)")
    overhead = rep["details"]["checkpoint_async_step_overhead"]
    assert overhead < 1.2, (
        f"async checkpointing costs {overhead}x on the step path")
    async_s = rep["details"]["checkpoint_async_step_s"]
    sync_s = rep["details"]["checkpoint_sync_step_s"]
    assert async_s < sync_s, (
        f"async step time ({async_s}s) does not beat sync save "
        f"({sync_s}s) — commit latency is not hidden")
    # Tracing plane A/B (README "Tracing & timeline"): RT_TRACING unset
    # must cost nothing (within run-to-run noise of the main run's rate),
    # and sampled-on (RT_TRACE_SAMPLE=0.01) must stay under 5% overhead.
    t_off = rep["details"].get("tracing_off_tasks_s")
    t_on = rep["details"].get("tracing_on_tasks_s")
    assert t_off and t_on, (
        "tracing_overhead A/B missing (bench skipped it: see its stderr)")
    main_rate = rep["details"]["single_client_tasks_async"]
    assert rep["details"]["tracing_off_best_tasks_s"] > 0.75 * main_rate, (
        f"tracing-off path ({t_off}/s median) regressed vs the baseline "
        f"run ({main_rate}/s) — the off path is supposed to be free")
    # Gate on the lane's median-of-interleaved-pairs ratio (not a leg
    # max): single legs on a 1-core CI box swing well past 5% both ways.
    # The bound is 1.05x whenever the box can resolve 5%, widened to 3x
    # the legs' relative MAD when ambient noise makes 5% unresolvable
    # (the bench logs the bound it derived).
    t_bound = rep["details"]["tracing_overhead_bound"]
    assert rep["details"]["tracing_overhead"] <= t_bound, (
        f"sampled-on tracing costs {rep['details']['tracing_overhead']}x "
        f"(off {t_off}/s vs on {t_on}/s medians) — budget is 1.05x "
        f"(noise-widened gate: {t_bound}x)")
    # Telemetry plane A/B (README "Telemetry & profiling"): sampling off
    # must cost nothing (no sampler thread, heartbeat frames byte-identical
    # — the wire shape itself is pinned in tier-1), and armed sampling at
    # a 1s cadence must stay under 5% on the task-throughput lane.
    m_off = rep["details"].get("telemetry_off_tasks_s")
    m_on = rep["details"].get("telemetry_on_tasks_s")
    assert m_off and m_on, (
        "telemetry_overhead A/B missing (bench skipped it: see its stderr)")
    assert rep["details"]["telemetry_off_best_tasks_s"] > 0.75 * main_rate, (
        f"telemetry-off path ({m_off}/s median) regressed vs the baseline "
        f"run ({main_rate}/s) — the off path is supposed to be free")
    m_bound = rep["details"]["telemetry_overhead_bound"]
    assert rep["details"]["telemetry_overhead"] <= m_bound, (
        f"armed telemetry costs {rep['details']['telemetry_overhead']}x "
        f"(off {m_off}/s vs on {m_on}/s medians) — budget is 1.05x "
        f"(noise-widened gate: {m_bound}x)")
    # Event plane A/B (README "Cluster events"): emission is always-on by
    # default, so the default-on driver task hot path must sit within the
    # noise bound of RT_EVENTS_BUFFER=0 — nothing on the per-task path
    # emits; lifecycle transitions are orders of magnitude rarer.
    e_off = rep["details"].get("events_off_tasks_s")
    e_on = rep["details"].get("events_on_tasks_s")
    assert e_off and e_on, (
        "events_overhead A/B missing (bench skipped it: see its stderr)")
    e_bound = rep["details"]["events_overhead_bound"]
    assert rep["details"]["events_overhead"] <= e_bound, (
        f"always-on event plane costs {rep['details']['events_overhead']}x "
        f"(off {e_off}/s vs on {e_on}/s medians) — budget is 1.05x "
        f"(noise-widened gate: {e_bound}x)")
    # Compiled dataflow plane (ISSUE 15 acceptance): steady-state
    # execution of a 3-stage chain through pre-wired shm channels must
    # beat the SAME chain as direct-dispatch .remote() calls by >= 3x
    # us/step (ratio of interleaved-pair medians; README "Compiled
    # graphs") — taking the owner/controller out of the steady-state
    # loop is the plane's reason to exist.
    d_on = rep["details"].get("dag_steady_state_on_tasks_s")
    d_off = rep["details"].get("dag_steady_state_off_tasks_s")
    assert d_on and d_off, (
        "dag_steady_state lane missing (bench skipped it: see its stderr)")
    d_speedup = rep["details"]["dag_steady_state_speedup"]
    assert d_speedup >= 3.0, (
        f"compiled DAG is only {d_speedup}x direct dispatch "
        f"({rep['details']['dag_compiled_us_step']} vs "
        f"{rep['details']['dag_direct_us_step']} us/step medians) — "
        f"the zero-RPC steady state is not earning its keep")
    # Serving hot loop (ISSUE 13 acceptance): end-to-end SSE streaming
    # decode under 4 concurrent clients must hold >= 0.5x of the SAME
    # engine's isolated rate (vs ~0.045x on the per-token reply path the
    # token ring replaced). The bound is the spec'd 0.5 floor, noise-
    # widened downward on boxes whose legs can't resolve it (README
    # "Serving hot loop").
    e2e = rep["details"].get("serve_decode_e2e_tok_s")
    iso = rep["details"].get("serve_decode_engine_tok_s")
    assert e2e and iso, (
        "serve_decode_e2e lane missing (bench skipped it: see its stderr)")
    s_ratio = rep["details"]["serve_decode_e2e_ratio"]
    s_bound = rep["details"]["serve_decode_e2e_bound"]
    assert s_ratio >= s_bound, (
        f"end-to-end streaming decode is {s_ratio}x of the isolated "
        f"engine ({e2e} vs {iso} tok/s medians) — the serving path is "
        f"eating throughput again (gate bound {s_bound}x)")
    # Pipeline-parallel decode (ISSUE 18 acceptance): the 2-stage
    # PipelinedEngine vs the single-process engine at matched total
    # parameters. The throughput bound is core-aware (the bench derives
    # it: 1.3x where both stages have cores, a sanity floor on 1-core
    # boxes that time-slice the stage processes), and the zero-RPC
    # steady state is unconditional: over the measured window the stage
    # resolve counters must show placeholder pins flowing on activation
    # edges and ZERO export/fetch RPCs (README "Pipeline-parallel
    # serving").
    p_single = rep["details"].get("llm_pipeline_single_tok_s")
    p_pipe = rep["details"].get("llm_pipeline_tok_s")
    assert p_single and p_pipe, (
        "llm_pipeline_decode lane missing (bench skipped it: see its "
        "stderr)")
    p_ratio = rep["details"]["llm_pipeline_ratio"]
    p_bound = rep["details"]["llm_pipeline_bound"]
    assert p_ratio >= p_bound, (
        f"pipeline decode is {p_ratio}x of single-process ({p_pipe} vs "
        f"{p_single} tok/s medians) — below the core-aware gate bound "
        f"({p_bound}x)")
    assert rep["details"]["llm_pipeline_edge_pins"] > 0, (
        "no placeholder pins on activation edges — activations are "
        "riding the channels inline, not the device-object plane")
    assert rep["details"]["llm_pipeline_resolve_rpcs"] == 0, (
        f"{rep['details']['llm_pipeline_resolve_rpcs']} resolve RPCs in "
        f"the steady-state decode window — the zero-RPC contract is "
        f"broken")
    # Admission control A/B (ISSUE 17 acceptance): the armed-but-not-
    # binding admission plane must cost nothing on the handle path vs
    # RT_SERVE_ADMISSION=0 (median-of-interleaved-pairs ratio, noise-
    # widened bound — README "Overload & admission control").
    a_off = rep["details"].get("serve_admission_off_tasks_s")
    a_on = rep["details"].get("serve_admission_on_tasks_s")
    assert a_off and a_on, (
        "serve_admission A/B missing (bench skipped it: see its stderr)")
    a_bound = rep["details"]["serve_admission_overhead_bound"]
    assert rep["details"]["serve_admission_overhead"] <= a_bound, (
        f"admission plane costs {rep['details']['serve_admission_overhead']}"
        f"x on the handle path (off {a_off}/s vs on {a_on}/s medians) — "
        f"budget is 1.05x (noise-widened gate: {a_bound}x)")
    # Overload storm (ISSUE 17 acceptance): ~10x load on a capped LLM
    # deployment — EVERY client resolves (admitted or typed shed, zero
    # hangs), overload sheds exist, queue-full sheds return in
    # milliseconds (well under a decode-chunk interval), and the sheds
    # protect real goodput for the admitted streams.
    o_clients = rep["details"].get("serve_overload_clients")
    assert o_clients, (
        "serve_overload lane missing (bench skipped it: see its stderr)")
    assert rep["details"]["serve_overload_resolved"] == o_clients, (
        f"{o_clients - rep['details']['serve_overload_resolved']} clients "
        f"hung under overload — shed-not-stall is broken")
    assert rep["details"]["serve_overload_shed_total"] > 0, (
        "10x overload shed nothing — admission budgets are not binding")
    assert rep["details"]["serve_overload_admitted"] > 0, (
        "overload admitted nothing — the deployment is unavailable, "
        "not overloaded")
    shed_p50 = rep["details"].get("serve_overload_shed_ms_p50")
    if shed_p50 is not None:
        assert shed_p50 < 250.0, (
            f"queue-full sheds take {shed_p50}ms at median — rejection "
            f"must be immediate, not queued behind the overload")
    assert rep["details"]["serve_overload_goodput_tok_s"] > 0, (
        "admitted streams made no goodput under overload")
    # Cross-host streaming & multi-proxy fan-out (ISSUE 20 acceptance):
    # with RT_STREAM_FORCE_PUSH=1 every replica answers the handshake the
    # way a remote-host replica would, so the A/B isolates the push-stream
    # transport against the per-item fallback it replaces. The bound is
    # core-aware (the bench derives it: 1.5x where the proxy, replicas and
    # clients get cores; a sanity floor on 1-core boxes). The 2-proxy
    # fleet must hold aggregate goodput against a single proxy — the
    # replica-set is the bottleneck, the ingress must not be — and p99
    # TTFT under the 16-client heavy-tailed storm stays bounded relative
    # to serve_decode_e2e's lightly-loaded baseline (README "Cross-host
    # streaming & multi-proxy").
    f_push = rep["details"].get("serve_fanout_push_tok_s")
    f_item = rep["details"].get("serve_fanout_peritem_tok_s")
    assert f_push and f_item, (
        "serve_fanout lane missing (bench skipped it: see its stderr)")
    f_ratio = rep["details"]["serve_fanout_push_ratio"]
    f_bound = rep["details"]["serve_fanout_push_bound"]
    assert f_ratio >= f_bound, (
        f"push-stream transport is {f_ratio}x of the per-item fallback "
        f"({f_push} vs {f_item} tok/s medians) — below the core-aware "
        f"gate bound ({f_bound}x)")
    fm_ratio = rep["details"]["serve_fanout_multi_ratio"]
    fm_bound = rep["details"]["serve_fanout_multi_bound"]
    assert fm_ratio >= fm_bound, (
        f"2-proxy fleet moves {fm_ratio}x of the single proxy "
        f"({rep['details']['serve_fanout_multi_tok_s']} vs "
        f"{rep['details']['serve_fanout_single_tok_s']} tok/s) — the "
        f"ingress fan-out is eating goodput (bound {fm_bound}x)")
    f_p99 = rep["details"]["serve_fanout_ttft_p99_ms"]
    f_p99_bound = rep["details"]["serve_fanout_ttft_p99_bound_ms"]
    assert f_p99 <= f_p99_bound, (
        f"p99 TTFT under the fan-out storm is {f_p99}ms (bound "
        f"{f_p99_bound}ms) — clients are sitting unacknowledged")
    assert rep["details"].get("serve_fanout_ttft_p50_ms", 0) > 0
    # The lightly-loaded serve lane records TTFT percentiles too (the
    # fan-out bound is derived from them when present).
    assert rep["details"].get("serve_decode_ttft_p99_ms", 0) > 0, (
        "serve_decode_e2e TTFT percentiles missing")
    # Streaming shuffle (ISSUE 19 acceptance): the pipelined exchange vs
    # the barrier mode of the SAME multi-block random_shuffle, in GB/s.
    # The floor is core-aware (the bench derives it: 1.5x where map and
    # consolidation tasks can overlap, a noise-widened sanity floor on
    # 1-core boxes where the extra consolidation hops are pure overhead
    # — README "Data plane"), and the distributed rate must be a real
    # fraction of a single-process numpy take() over the same rows.
    sh_pipe = rep["details"].get("data_shuffle_gbps")
    sh_barrier = rep["details"].get("data_shuffle_barrier_gbps")
    assert sh_pipe and sh_barrier, (
        "data_shuffle A/B missing (bench skipped it: see its stderr)")
    sh_speedup = rep["details"]["data_shuffle_speedup"]
    sh_floor = rep["details"]["data_shuffle_speedup_floor"]
    assert sh_speedup >= sh_floor, (
        f"pipelined shuffle is {sh_speedup}x barrier mode ({sh_pipe} vs "
        f"{sh_barrier} GB/s medians) — below the core-aware gate floor "
        f"({sh_floor}x)")
    sh_vs_local = rep["details"]["data_shuffle_vs_local"]
    sh_local_floor = rep["details"]["data_shuffle_vs_local_floor"]
    assert sh_vs_local >= sh_local_floor, (
        f"distributed shuffle moves {sh_vs_local}x of the single-process "
        f"numpy baseline ({rep['details']['data_shuffle_local_gbps']} "
        f"GB/s) — below the {sh_local_floor} floor for this core class")
    # Streaming ingest (ISSUE 19 acceptance): iter_batches must stream
    # the dataset end to end (read tasks through the bounded window into
    # driver-side numpy batches) at a nonzero rate — a hang or a dropped
    # row fails inside the bench lane itself.
    assert rep["details"].get("data_ingest_gbps", 0) > 0, (
        "data_ingest lane missing (bench skipped it: see its stderr)")
