"""runtime_env: working_dir / py_modules packaging + activation.

Parity target: reference python/ray/tests/test_runtime_env_working_dir.py
(_private/runtime_env/working_dir.py, py_modules.py, packaging.py): local
dirs are zipped, content-addressed in the KV, extracted on the executing
node; tasks see working_dir as cwd, py_modules on sys.path.
"""

import os

import pytest

import ray_tpu


def test_task_working_dir(ray_start_2cpu, tmp_path):
    wd = tmp_path / "app"
    wd.mkdir()
    (wd / "data.txt").write_text("hello-from-working-dir")

    @ray_tpu.remote(runtime_env={"working_dir": str(wd)})
    def read_data():
        with open("data.txt") as f:
            return f.read()

    assert ray_tpu.get(read_data.remote(), timeout=60) == "hello-from-working-dir"

    # Pooled workers restore cwd between tasks: a no-env task must not see it.
    @ray_tpu.remote
    def no_env_cwd_has_data():
        return os.path.exists("data.txt")

    assert ray_tpu.get(no_env_cwd_has_data.remote(), timeout=60) is False


def test_task_py_modules(ray_start_2cpu, tmp_path):
    mod_dir = tmp_path / "mods"
    pkg = mod_dir / "my_testmod"
    pkg.mkdir(parents=True)
    (pkg / "__init__.py").write_text("MAGIC = 1234\n")

    @ray_tpu.remote(runtime_env={"py_modules": [str(mod_dir)]})
    def use_module():
        import my_testmod

        return my_testmod.MAGIC

    assert ray_tpu.get(use_module.remote(), timeout=60) == 1234


def test_actor_working_dir(ray_start_2cpu, tmp_path):
    wd = tmp_path / "actor_app"
    wd.mkdir()
    (wd / "cfg.txt").write_text("actor-config")

    @ray_tpu.remote(runtime_env={"working_dir": str(wd)})
    class Cfg:
        def read(self):
            with open("cfg.txt") as f:
                return f.read()

    c = Cfg.remote()
    assert ray_tpu.get(c.read.remote(), timeout=60) == "actor-config"


def test_unsupported_runtime_env_rejected(ray_start_2cpu):
    @ray_tpu.remote(runtime_env={"pip": ["requests"]})
    def f():
        return 1

    with pytest.raises(ValueError, match="not supported"):
        f.remote()
