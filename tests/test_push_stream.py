"""Push-stream transport: the StreamRing record contract over rpc
(README "Cross-host streaming & multi-proxy").

Unit layer: one in-process hub + writer pair per test — the same wiring a
proxy/replica pair uses, minus the processes — driven through the ring
calling convention (write / read_batch / close). Chaos layer: the rpc
FaultInjector's "stream"-labeled rules prove the attributed-death
contract frame by frame: a duplicated frame is discarded (byte-identical
outcome), a dropped frame — middle OR tail — surfaces as StreamSevered
(attributed outcome), never silent corruption. Serve layer:
RT_STREAM_FORCE_PUSH=1 makes every replica answer the ring handshake the
way a remote-host replica would, so the full proxy->replica SSE path
runs over the push transport on one box.
"""

import glob
import json
import os
import socket
import threading
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu._private import rpc
from ray_tpu.dag.push_stream import (
    PushStreamHub,
    PushStreamWriter,
    StreamSevered,
)
from ray_tpu.dag.stream import RingClosed


def _wait(pred, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


class _Pair:
    """One hub + one connected writer on private event-loop threads."""

    def __init__(self, window: int = 64 * 1024):
        self.io = rpc.EventLoopThread(name="ps-hub")
        self.hub = PushStreamHub()
        self.io.run(self.hub.start("127.0.0.1"))
        self.reader = self.hub.open("s", window)
        self.writer = PushStreamWriter(self.hub.spec("s", window))

    def drain(self, timeout=10.0):
        """Read to end-of-stream; returns (records, terminal exception)."""
        got = []
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                got.extend(self.reader.read_batch(timeout=0.5))
            except TimeoutError:
                continue
            except (RingClosed, StreamSevered) as e:
                return got, e
        raise AssertionError("stream never terminated")

    def close(self):
        for fn in (self.writer.close, lambda: self.io.run(self.hub.stop())):
            try:
                fn()
            except Exception:
                pass
        self.io.stop()


# ------------------------------------------------------------- unit layer
def test_roundtrip_batch_drain():
    """Records arrive in order through the ring calling convention, one
    read_batch drains a buffered burst, and close() lands as RingClosed
    only after everything is drained (the bug class: s_close overtaking
    the final coalesced s_data frame)."""
    p = _Pair()
    try:
        for i in range(500):
            p.writer.write(("item", i))
        p.writer.write(("end", None))
        p.writer.close()
        got, term = p.drain()
        assert isinstance(term, RingClosed)
        assert got == [("item", i) for i in range(500)] + [("end", None)]
    finally:
        p.close()


def test_burst_coalesces_into_one_frame():
    """Records written while the IO loop is busy accrete behind one
    scheduled flush and ride ONE s_data frame — the per-burst (not
    per-record) framing the transport exists for."""
    p = _Pair()
    try:
        p.writer.write("warm")
        _wait(lambda: p.writer._seq == 1 and p.writer._inflight == 0, 5,
              "warm flush")
        # Park the writer's IO loop; everything written meanwhile shares
        # the single flush that runs when it wakes.
        p.writer._loop.call_soon_threadsafe(time.sleep, 0.3)
        for i in range(50):
            p.writer.write(i)
        _wait(lambda: len(p.reader._recs) >= 51, 5, "burst delivery")
        assert p.writer._seq == 2, "burst split across frames"
        batch = p.reader.read_batch(timeout=1)
        assert batch == ["warm"] + list(range(50))
    finally:
        p.close()


def test_backpressure_parks_writer_until_consumer_drains():
    """A stalled consumer exhausts the credit window: write() parks (and
    times out if asked to), and one consumer drain releases it — bounded
    buffering, exactly like a full shm ring."""
    p = _Pair(window=8192)
    try:
        blob = "x" * 1000
        with pytest.raises(TimeoutError):
            for _ in range(32):  # credit 8KB + pending 8KB < 32KB offered
                p.writer.write(blob, timeout=0.3)
        drained = p.reader.read_batch(timeout=5)
        assert drained, "consumer saw nothing despite a full window"
        p.writer.write(blob, timeout=5)  # credit returned: unparked
    finally:
        p.close()


def test_oversize_record_rejected():
    p = _Pair(window=8192)
    try:
        with pytest.raises(ValueError):
            p.writer.write("y" * 10000)
        p.writer.write("fits")  # the stream survives the rejection
        assert p.reader.read_batch(timeout=5) == ["fits"]
    finally:
        p.close()


def test_write_after_close_raises_ring_closed():
    p = _Pair()
    try:
        p.writer.write("a")
        p.writer.close()
        p.writer.close()  # idempotent
        with pytest.raises(RingClosed):
            p.writer.write("b")
        got, term = p.drain()
        assert got == ["a"] and isinstance(term, RingClosed)
    finally:
        p.close()


# ------------------------------------------------------------ chaos layer
@pytest.fixture
def stream_injector():
    inj = rpc.enable_fault_injection()
    inj.clear()
    yield inj
    inj.clear()
    rpc.disable_fault_injection()


def test_dup_frame_discarded_byte_identical(stream_injector):
    """A duplicated s_data frame is discarded by seq — the consumer's
    record stream is byte-identical to the clean run."""
    rule = stream_injector.add_rule(
        "stream", "dup", direction="send", methods={"s_data"},
        after=1, times=1)
    p = _Pair()
    try:
        for i in range(20):
            p.writer.write(i)
            time.sleep(0.01)  # separate frames so the dup hits one
        p.writer.close()
        got, term = p.drain()
        assert isinstance(term, RingClosed)
        assert got == list(range(20)), "dup frame leaked records"
        assert rule.applied == 1
    finally:
        p.close()


def test_dropped_middle_frame_severs_with_gap(stream_injector):
    """A dropped s_data frame is detected as a seq gap by its successor
    and surfaces as StreamSevered — attributed, never silently skipped."""
    stream_injector.add_rule(
        "stream", "drop", direction="send", methods={"s_data"},
        after=2, times=1)
    p = _Pair()
    try:
        for i in range(20):
            p.writer.write(i)
            time.sleep(0.01)
        p.writer.close()
        got, term = p.drain()
        assert isinstance(term, StreamSevered), (got, term)
        assert "gap" in str(term)
        assert got == got[: len(got)], "records out of order"
        assert len(got) < 20, "drop delivered everything anyway"
    finally:
        p.close()


def test_dropped_tail_frame_severs_via_close_seq(stream_injector):
    """A lost TAIL frame has no successor to expose its gap — the s_close
    record carries the producer's final frame count and catches it. The
    outcome is StreamSevered, not a clean close missing records."""
    p = _Pair()
    try:
        for i in range(10):
            p.writer.write(i)
            time.sleep(0.01)
        _wait(lambda: p.writer._inflight == 0, 5, "frames on the wire")
        # Arm the drop for the LAST frame only, then write it.
        stream_injector.add_rule(
            "stream", "drop", direction="send", methods={"s_data"},
            times=1)
        p.writer.write("tail")
        p.writer.close()
        got, term = p.drain()
        assert isinstance(term, StreamSevered), (got, term)
        assert "tail" not in got
        assert "lost tail" in str(term)
    finally:
        p.close()


def test_severed_connection_surfaces_both_ends(stream_injector):
    """An injected sever mid-stream: the reader raises StreamSevered and
    a parked/subsequent write raises too — neither side hangs."""
    stream_injector.add_rule(
        "stream", "sever", direction="send", methods={"s_data"}, after=1)
    p = _Pair()
    try:
        p.writer.write("a")
        time.sleep(0.05)
        with pytest.raises((StreamSevered, TimeoutError)):
            for _ in range(200):
                p.writer.write("b", timeout=0.1)
                time.sleep(0.01)
        got, term = p.drain()
        assert isinstance(term, StreamSevered)
    finally:
        p.close()


# ------------------------------------------------------------ serve layer
CFG_KW = dict(vocab_size=384, d_model=64, n_layers=2, n_heads=4,
              max_seq=128)


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _openai_app(port, **kw):
    from ray_tpu import serve
    from ray_tpu.llm import LLMConfig
    from ray_tpu.llm.openai import build_openai_app

    app = build_openai_app(LLMConfig(**CFG_KW), max_batch=4, decode_chunk=4,
                           default_max_tokens=8, **kw)
    serve.run(app, route_prefix="/", port=port)


def _sse_request(base, max_tokens, timeout=120):
    body = json.dumps({"model": "m", "prompt": "hello", "max_tokens":
                       max_tokens, "stream": True,
                       "temperature": 0.0}).encode()
    req = urllib.request.Request(base + "/v1/completions", data=body,
                                 headers={"Content-Type":
                                          "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)


def _drain_sse(resp):
    toks, events = [], []
    for line in resp:
        line = line.decode().strip()
        if not line.startswith("data: "):
            continue
        data = line[6:]
        if data == "[DONE]":
            break
        ev = json.loads(data)
        events.append(ev)
        toks.extend(ev.get("token_ids", []) or [])
    return toks, events


def _stats(base):
    return json.loads(urllib.request.urlopen(
        base + "/v1/stats", timeout=30).read())


def test_force_push_serve_stream_and_sigkill_attributed(shutdown_only,
                                                        monkeypatch):
    """One cluster, both halves of the serve-layer contract. Clean path:
    a full SSE decode with the replica forced onto the push transport —
    every requested token arrives, coalesced frames and all, and the
    stream terminates cleanly. Chaos path: replica SIGKILL mid-stream —
    the open SSE client gets a structured error naming the replica and
    the `ray-tpu events` pointer — never a hang, never a bare disconnect
    (the attributed-death contract, now over the rpc transport)."""
    monkeypatch.setenv("RT_STREAM_FORCE_PUSH", "1")
    ray_tpu.init(num_cpus=4)
    port = _free_port()
    _openai_app(port)
    base = f"http://127.0.0.1:{port}"
    toks, events = _drain_sse(_sse_request(base, 48))
    assert len(toks) == 48, f"lost tokens: {len(toks)}"
    assert all("error" not in ev for ev in events)
    pid = _stats(base)["pid"]

    resp = _sse_request(base, 96)
    got_err = {}
    deadline = time.monotonic() + 45
    killed = False
    for line in resp:
        line = line.decode().strip()
        if not line.startswith("data: "):
            continue
        data = line[6:]
        if data == "[DONE]":
            break
        ev = json.loads(data)
        if "error" in ev:
            got_err = ev["error"]
            break
        if not killed:
            os.kill(pid, 9)
            killed = True
        assert time.monotonic() < deadline, "no attributed error in 45s"
    assert killed, "stream ended before the kill landed"
    assert got_err, "stream ended with no structured error"
    assert "events" in got_err and "ray-tpu events" in got_err["events"]
    from ray_tpu import serve

    serve.shutdown()
