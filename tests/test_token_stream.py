"""Token-batch stream ring + the zero-sync serving reply path.

README "Serving hot loop": ring-level invariants (FIFO across wrap,
bounded-buffer backpressure, batch-per-wakeup draining), the
RT_TOKEN_RING=0 byte-identical fallback, and the chaos cases — client
disconnect mid-generation retires the engine slot (no slot leak),
engine-scheduler death and replica death surface attributed errors on
every open stream, never a hang.
"""

import glob
import json
import os
import socket
import threading
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.dag.stream import RingClosed, StreamRing

CFG_KW = dict(vocab_size=384, d_model=64, n_layers=2, n_heads=4,
              max_seq=128)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


# ------------------------------------------------------------- ring level
def test_ring_fifo_no_loss_across_wrap():
    """2000 records through a 4KB ring: every record arrives, in order —
    the ring wraps dozens of times (slot reuse at the byte level)."""
    ring = StreamRing(f"t_fifo_{os.getpid()}", 4096)
    n = 2000
    got: list = []

    def produce():
        for i in range(n):
            ring.write(("rec", i, b"x" * (i % 40)), timeout=30)
        ring.close_write()

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    try:
        while True:
            try:
                got.extend(ring.read_batch(timeout=30))
            except RingClosed:
                break
        assert [r[1] for r in got] == list(range(n))
        assert all(r[2] == b"x" * (r[1] % 40) for r in got)
    finally:
        t.join(timeout=10)
        ring.close(unlink=True)


def test_ring_read_batch_drains_burst_in_one_wakeup():
    ring = StreamRing(f"t_batch_{os.getpid()}", 1 << 16)
    try:
        for i in range(10):
            ring.write(i)
        assert ring.read_batch(timeout=1) == list(range(10))
        with pytest.raises(TimeoutError):
            ring.read_batch(timeout=0.05)
    finally:
        ring.close(unlink=True)


def test_ring_backpressure_producer_parks_bounded():
    """No consumer: the producer fills the BOUNDED ring then parks (write
    times out) instead of buffering unboundedly; a consumer draining later
    receives everything written, in order, and unparks further writes."""
    cap = 4096
    ring = StreamRing(f"t_bp_{os.getpid()}", cap)
    try:
        written = 0
        payload = b"y" * 100
        with pytest.raises(TimeoutError):
            while True:
                ring.write((written, payload), timeout=0.05)
                written += 1
        # Parked at the capacity bound: nothing close to unbounded growth.
        assert 0 < written <= cap // 100
        got = ring.read_batch(timeout=1)
        assert [r[0] for r in got] == list(range(written))
        ring.write((written, payload), timeout=1)  # space freed: unparked
        assert ring.read_batch(timeout=1)[0][0] == written
    finally:
        ring.close(unlink=True)


def test_ring_close_write_then_drained_raises():
    ring = StreamRing(f"t_close_{os.getpid()}", 4096)
    try:
        ring.write("a")
        ring.write("b")
        ring.close_write()
        assert ring.read_batch(timeout=1) == ["a", "b"]
        with pytest.raises(RingClosed):
            ring.read_batch(timeout=1)
        with pytest.raises(RingClosed):
            ring.write("c")
    finally:
        ring.close(unlink=True)


def test_ring_oversize_record_rejected():
    ring = StreamRing(f"t_big_{os.getpid()}", 4096)
    try:
        with pytest.raises(ValueError, match="record"):
            ring.write(b"z" * 4096)
    finally:
        ring.close(unlink=True)


def test_ring_attach_requires_existing():
    with pytest.raises(FileNotFoundError):
        StreamRing(f"t_missing_{os.getpid()}", 4096, _create=False)
    ring = StreamRing(f"t_attach_{os.getpid()}", 8192)
    try:
        peer = StreamRing.attach(ring.spec())
        ring.write("hello")
        assert peer.read_batch(timeout=1) == ["hello"]
        peer.close()
    finally:
        ring.close(unlink=True)


# ------------------------------------------------------------ engine level
@pytest.fixture(scope="module")
def engine():
    from ray_tpu.llm import LLMConfig
    from ray_tpu.llm.engine import ContinuousEngine

    eng = ContinuousEngine(LLMConfig(**CFG_KW), max_batch=4, decode_chunk=4)
    yield eng
    eng.shutdown()


def test_genstream_batch_delivery_one_wakeup_per_chunk(engine):
    """GenStream delivers token BATCHES: draining 32 tokens takes far
    fewer next_batch wakeups than tokens (one queue put per decode chunk,
    not per token — the satellite's no-per-token-wakeup pin)."""
    from ray_tpu.llm.engine import SamplingParams

    s = engine.submit([1, 2, 3], SamplingParams(temperature=0.0,
                                                max_tokens=32))
    batches = []
    while True:
        try:
            batches.append(s.next_batch(timeout=60))
        except StopIteration:
            break
    toks = [t for b in batches for t in b]
    assert len(toks) == 32
    # 32 tokens at decode_chunk=4 is ~9 queue puts (first token + 8
    # chunks); a per-token queue would need 32 wakeups.
    assert len(batches) <= 16, f"{len(batches)} wakeups for 32 tokens"
    # Batched delivery preserves the exact greedy sequence.
    ref = engine.submit([1, 2, 3], SamplingParams(temperature=0.0,
                                                  max_tokens=32)).tokens()
    assert toks == ref


def test_disconnect_churn_retires_slots_no_leak(engine):
    """Chaos satellite: consumers abandoning streams mid-generation (the
    client-disconnect shape) retire their slots and free KV/sampling
    state — 24 churned requests across 8 rounds reuse the same 4 slots
    and the engine drains to zero active every round."""
    from ray_tpu.llm.engine import SamplingParams

    for _ in range(8):
        streams = [engine.submit([7, 8, 9], SamplingParams(
            temperature=0.0, max_tokens=100)) for _ in range(3)]
        for s in streams:
            s.next(timeout=60)  # slot is live and decoding
            s.close()  # client gone
        deadline = time.monotonic() + 30
        while engine.num_active > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert engine.num_active == 0, "abandoned slots leaked"
    # The engine still serves fresh requests with exact token counts.
    toks = engine.submit([7, 8, 9], SamplingParams(
        temperature=0.0, max_tokens=12)).tokens()
    assert len(toks) == 12


def test_engine_scheduler_death_attributed_never_hangs():
    """Chaos satellite: the engine scheduler dying mid-stream surfaces an
    attributed error on EVERY open GenStream promptly — a consumer
    blocked in next() must never hang on a dead engine."""
    from ray_tpu.llm import LLMConfig
    from ray_tpu.llm.engine import ContinuousEngine, SamplingParams

    eng = ContinuousEngine(LLMConfig(**CFG_KW), max_batch=4, decode_chunk=4)
    try:
        streams = [eng.submit([1, 2], SamplingParams(
            temperature=0.0, max_tokens=120)) for _ in range(2)]
        for s in streams:
            s.next(timeout=60)  # both decoding
        eng._slots = None  # scheduler's next iteration dies uncaught
        for s in streams:
            with pytest.raises(RuntimeError, match="scheduler died"):
                deadline = time.monotonic() + 15
                while time.monotonic() < deadline:
                    s.next(timeout=15)
        assert not eng._running
        with pytest.raises(RuntimeError, match="shut down"):
            eng.submit([1], SamplingParams(max_tokens=1))
    finally:
        eng.shutdown()


# -------------------------------------------------------------- HTTP level
def _openai_app(port, **kw):
    from ray_tpu.llm import LLMConfig
    from ray_tpu.llm.openai import build_openai_app

    from ray_tpu import serve

    app = build_openai_app(LLMConfig(**CFG_KW), model_id="ring-llm",
                           max_batch=4, decode_chunk=4,
                           default_max_tokens=8, **kw)
    serve.run(app, route_prefix="/", port=port)


def _sse_request(base, max_tokens, timeout=120):
    body = json.dumps({"prompt": "hi", "max_tokens": max_tokens,
                       "temperature": 0.0, "stream": True}).encode()
    return urllib.request.Request(
        f"{base}/v1/completions", data=body,
        headers={"Content-Type": "application/json"})


def _drain_sse(resp):
    toks, events = [], 0
    for line in resp:
        line = line.decode().strip()
        if not line.startswith("data: "):
            continue
        payload = line[6:]
        if payload == "[DONE]":
            break
        events += 1
        toks.extend(json.loads(payload).get("token_ids", []))
    return toks, events


def _stats(base):
    with urllib.request.urlopen(f"{base}/v1/stats", timeout=30) as r:
        return json.loads(r.read())


def test_sse_ring_concurrent_clients_fifo_no_loss(shutdown_only):
    """4 concurrent streaming clients over the token ring: every client
    receives its full greedy sequence in order (no token loss or cross-
    slot mixing across engine slot reuse), and multi-token arrivals
    coalesce into fewer SSE events than tokens."""
    from ray_tpu import serve

    ray_tpu.init(num_cpus=4)
    port = _free_port()
    _openai_app(port)
    base = f"http://127.0.0.1:{port}"
    try:
        results: dict = {}

        def client(i):
            with urllib.request.urlopen(_sse_request(base, 24),
                                        timeout=180) as r:
                results[i] = _drain_sse(r)

        for round_ in range(2):  # second round reuses the freed slots
            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=180)
            seqs = [tuple(results[i][0]) for i in range(4)]
            assert all(len(s) == 24 for s in seqs), seqs
            # Same greedy prompt => identical sequences on every client.
            assert len(set(seqs)) == 1
        # Coalescing: a 24-token stream arrives in well under 24 events.
        _toks, events = results[0]
        assert events < 24, f"{events} SSE events for 24 tokens"
        assert _stats(base)["active"] == 0
    finally:
        serve.shutdown()


def test_sse_token_ring_off_byte_identical_fallback(monkeypatch,
                                                    shutdown_only):
    """RT_TOKEN_RING=0: the classic per-item streaming-generator reply
    path serves the stream — and no stream ring is ever created."""
    from ray_tpu import serve

    monkeypatch.setenv("RT_TOKEN_RING", "0")
    ray_tpu.init(num_cpus=4)
    port = _free_port()
    _openai_app(port)
    base = f"http://127.0.0.1:{port}"
    try:
        rings_seen = []
        toks = []
        with urllib.request.urlopen(_sse_request(base, 12),
                                    timeout=180) as r:
            for line in r:
                rings_seen.extend(glob.glob("/dev/shm/rtring_sse_*"))
                line = line.decode().strip()
                if not line.startswith("data: "):
                    continue
                if line[6:] == "[DONE]":
                    break
                toks.extend(json.loads(line[6:]).get("token_ids", []))
        assert len(toks) == 12
        assert rings_seen == [], f"knob off but rings exist: {rings_seen}"
    finally:
        serve.shutdown()


def test_sse_client_disconnect_frees_engine_slot(shutdown_only):
    """Chaos satellite at the HTTP layer: a client dropping its SSE
    connection mid-generation retires the engine slot (observed via
    /v1/stats) instead of decoding to max_tokens for nobody."""
    from ray_tpu import serve

    ray_tpu.init(num_cpus=4)
    port = _free_port()
    _openai_app(port)
    base = f"http://127.0.0.1:{port}"
    try:
        # Warm the engine (first request pays the compiles).
        with urllib.request.urlopen(_sse_request(base, 4), timeout=180) as r:
            _drain_sse(r)
        r = urllib.request.urlopen(_sse_request(base, 120), timeout=180)
        r.readline()  # first SSE event: the stream is live
        assert _stats(base)["active"] >= 1
        r.close()  # client disconnect
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if _stats(base)["active"] == 0:
                break
            time.sleep(0.1)
        assert _stats(base)["active"] == 0, "disconnected stream leaked slot"
        # The replica still serves a full request afterwards.
        with urllib.request.urlopen(_sse_request(base, 6), timeout=180) as r:
            toks, _ = _drain_sse(r)
        assert len(toks) == 6
    finally:
        serve.shutdown()


def test_sse_replica_death_attributed_never_hangs(shutdown_only):
    """Chaos satellite: the engine-hosting replica dying mid-stream ends
    every open SSE stream with an ATTRIBUTED error event within the
    failure-detection deadline — never a hang."""
    from ray_tpu import serve

    ray_tpu.init(num_cpus=4)
    port = _free_port()
    _openai_app(port)
    base = f"http://127.0.0.1:{port}"
    try:
        with urllib.request.urlopen(_sse_request(base, 4), timeout=180) as r:
            _drain_sse(r)  # warm compiles
        pid = _stats(base)["pid"]
        r = urllib.request.urlopen(_sse_request(base, 120), timeout=60)
        r.readline()  # stream is live
        os.kill(pid, 9)
        lines = []
        t0 = time.monotonic()
        try:
            for line in r:
                lines.append(line.decode().strip())
                if lines[-1] == "data: [DONE]":
                    break
        except Exception as e:  # connection torn down is also a fast end
            lines.append(f"connection-error: {e!r}")
        took = time.monotonic() - t0
        assert took < 45, f"stream hung {took:.0f}s after replica death"
        err_lines = [ln for ln in lines if "error" in ln.lower()]
        assert err_lines, f"no attributed error surfaced: {lines[-3:]}"
        assert any("actor" in ln.lower() or "died" in ln.lower()
                   or "connection-error" in ln for ln in err_lines), err_lines
    finally:
        serve.shutdown()
