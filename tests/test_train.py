"""JaxTrainer end-to-end (BASELINE north-star #1: DataParallelTrainer
MNIST-MLP on 2 workers) + failure-policy restart from checkpoint.

reference tests: python/ray/train/tests/test_data_parallel_trainer.py.
"""

import os
import pickle
import tempfile

import numpy as np
import pytest

import ray_tpu
from ray_tpu.train import (
    Checkpoint,
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)


def mnist_train_loop(config):
    """Synthetic-MNIST MLP: pjit over the worker's local devices, DP across
    workers via host allreduce."""
    import jax
    import jax.numpy as jnp
    import optax

    import ray_tpu.train as train
    from ray_tpu.models.mlp import MLP, loss_fn
    from ray_tpu.train import jax_utils

    ctx = train.get_context()
    rank, world = ctx.get_world_rank(), ctx.get_world_size()

    rng = np.random.RandomState(rank)
    x = rng.rand(config["batch"], 28, 28).astype("float32")
    y = (rng.rand(config["batch"]) * 10).astype("int32")

    model = MLP(hidden=32)
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(x[:1]))
    start_step = 0
    ckpt = train.get_checkpoint()
    if ckpt is not None:
        with open(os.path.join(ckpt.path, "state.pkl"), "rb") as f:
            state = pickle.load(f)
        params = state["params"]
        start_step = state["step"] + 1

    opt = optax.adam(1e-2)
    opt_state = opt.init(params)

    @jax.jit
    def grad_step(p, batch):
        return jax.value_and_grad(lambda q: loss_fn(model, q, batch))(p)

    for step in range(start_step, config["steps"]):
        if config.get("slow_step_s"):
            import time as _t

            _t.sleep(config["slow_step_s"])
        loss, grads = grad_step(params, (jnp.asarray(x), jnp.asarray(y)))
        grads = jax_utils.sync_gradients(grads)
        grads = jax.tree_util.tree_map(jnp.asarray, grads)
        updates, opt_state = opt.update(grads, opt_state, params)
        import optax as _o

        params = _o.apply_updates(params, updates)
        if config.get("die_at") is not None and step == config["die_at"] and rank == 0 \
                and train.get_session().restart_index == 0:
            os._exit(1)  # simulated worker crash (first attempt only)
        if rank == 0:
            with tempfile.TemporaryDirectory() as d:
                with open(os.path.join(d, "state.pkl"), "wb") as f:
                    pickle.dump({"params": params, "step": step}, f)
                train.report({"loss": float(loss), "step": step},
                             checkpoint=Checkpoint(d))
        else:
            train.report({"loss": float(loss), "step": step})
    return {"final_loss": float(loss), "rank": rank}


def test_jax_trainer_mnist_2workers(ray_start_4cpu, tmp_path):
    # 12 steps, not 8: adam(1e-2) spikes the loss on its first update
    # (second-moment warmup) and needs steps to come back under the
    # initial value on BOTH workers' shards — at 8, one worker still sits
    # at 2.386 vs its 2.311 start on this jax/optax build, so the old
    # positional "loss decreased" assert passed or failed depending on
    # which worker's report happened to drain last (a real full-suite
    # flake). By step 11 both shards are clearly converged
    # (2.33/2.31 -> 3.30/3.38 -> ... -> 2.19/2.20).
    trainer = JaxTrainer(
        mnist_train_loop,
        train_loop_config={"batch": 64, "steps": 12},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="mnist_e2e", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics is not None and "loss" in result.metrics
    assert result.checkpoint is not None
    # loss decreased over training — compared BY STEP, not by history
    # position: metrics_history interleaves both workers' reports in drain
    # order, so a positional losses[-1] reads whichever worker drained
    # last (drain order varies under CI load).
    by_step: dict = {}
    for m in result.metrics_history:
        if m.get("step") is not None:
            by_step.setdefault(m["step"], []).append(m["loss"])
    first, last = min(by_step), max(by_step)
    assert last == 11
    mean = lambda xs: sum(xs) / len(xs)  # noqa: E731 — workers' shard
    # losses differ; the group-level claim is about their mean per step
    assert mean(by_step[last]) < mean(by_step[first]), by_step
    # checkpoint is loadable
    with open(os.path.join(result.checkpoint.path, "state.pkl"), "rb") as f:
        state = pickle.load(f)
    assert state["step"] == 11


def test_jax_trainer_failure_restart(ray_start_4cpu, tmp_path):
    """Worker dies mid-run; FailureConfig restarts the group from the last
    checkpoint and training completes (reference failure_policy.py:14)."""
    trainer = JaxTrainer(
        mnist_train_loop,
        train_loop_config={"batch": 32, "steps": 6, "die_at": 3},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="mnist_ft", storage_path=str(tmp_path),
                             failure_config=FailureConfig(max_failures=2)),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    with open(os.path.join(result.checkpoint.path, "state.pkl"), "rb") as f:
        state = pickle.load(f)
    assert state["step"] == 5
    steps = [m["step"] for m in result.metrics_history]
    assert 5 in steps and steps.count(2) >= 1  # progressed past the crash


def test_sharded_state_checkpoint_via_report(ray_start_4cpu, tmp_path):
    """train.report(checkpoint=<state pytree>) rides the async sharded
    engine: EVERY rank calls report (rank 0 commits after all ranks'
    shard metadata lands in storage), the controller only learns of
    COMMITTED checkpoints, and the result checkpoint restores bitwise —
    including onto a different world size (here: the driver, world=1)."""
    import numpy as np

    def loop(config):
        import numpy as np

        import ray_tpu.train as train

        ctx = train.get_context()
        rank = ctx.get_world_rank()
        for step in range(3):
            state = {"params": {"w": np.full((8, 4), float(step))},
                     "step": step, "rank_of_writer": 0}
            train.report({"step": step, "rank": rank}, checkpoint=state)

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="sharded_ck", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.checkpoint is not None
    from ray_tpu.train import checkpoint as ckpt_mod

    man = ckpt_mod.load_manifest(result.checkpoint.path)
    assert man is not None and man["world_size"] == 2
    st = ckpt_mod.restore(result.checkpoint.path)
    assert np.array_equal(st["params"]["w"], np.full((8, 4), 2.0))
    assert st["step"] == 2


def test_jax_trainer_user_error_no_retry(ray_start_2cpu, tmp_path):
    def bad_loop(config):
        raise ValueError("intentional")

    trainer = JaxTrainer(
        bad_loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="bad", storage_path=str(tmp_path),
                             failure_config=FailureConfig(max_failures=3)),
    )
    result = trainer.fit()
    assert result.error is not None and "intentional" in result.error


@pytest.mark.skip(
    reason="environment-bound: this jaxlib build's CPU backend rejects "
           "cross-process computations (XlaRuntimeError: 'Multiprocess "
           "computations aren't implemented on the CPU backend') — the "
           "jax.distributed rendezvous/coordinator path it exercises DOES "
           "come up (service starts, both procs connect, process_count==2); "
           "only the global-mesh device_put/psum needs real multi-host XLA "
           "(TPU/GPU). Re-enable on hardware or a jaxlib with CPU gloo "
           "collectives.")
def test_jax_distributed_global_mesh(ray_start_4cpu, tmp_path):
    """ScalingConfig(jax_distributed=True): 2 worker processes x 4 virtual
    CPU devices each form ONE 8-device global mesh via
    jax.distributed.initialize (coordinator rendezvous over the controller
    KV), and a psum over the global mesh sees every device."""

    def loop(config):
        import jax
        import jax.numpy as jnp

        import ray_tpu.train as train
        from ray_tpu.train.jax_utils import global_mesh_from_distributed

        assert jax.process_count() == 2
        assert len(jax.devices()) == 8, jax.devices()
        mesh = global_mesh_from_distributed(axis_names=("dp",))
        from jax.sharding import NamedSharding, PartitionSpec as P

        ones = jnp.ones((8, 4))
        sharded = jax.device_put(ones, NamedSharding(mesh, P("dp")))
        total = float(jax.jit(
            lambda x: jnp.sum(x),
            in_shardings=(NamedSharding(mesh, P("dp")),))(sharded))
        train.report({"total": total,
                      "devices": len(jax.devices()),
                      "procs": jax.process_count()})

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(
            num_workers=2, jax_distributed=True,
            worker_env={"XLA_FLAGS": "--xla_force_host_platform_device_count=4",
                        "JAX_PLATFORMS": "cpu"}),
        run_config=RunConfig(storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["devices"] == 8
    assert result.metrics["procs"] == 2
    assert result.metrics["total"] == 32.0


def test_elastic_recovery_on_node_loss(ray_start_cluster, tmp_path):
    """A node dies mid-training and the cluster can no longer place the
    full quorum: with min_workers set, the group restarts SMALLER from the
    checkpoint and finishes (reference train v2 elastic ScalingPolicy),
    instead of waiting forever for capacity that is gone."""
    import threading
    import time as _time

    cluster = ray_start_cluster
    side = cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address)

    trainer = JaxTrainer(
        mnist_train_loop,
        train_loop_config={"batch": 32, "steps": 8, "slow_step_s": 0.4},
        scaling_config=ScalingConfig(num_workers=3, min_workers=1),
        run_config=RunConfig(name="elastic", storage_path=str(tmp_path),
                             failure_config=FailureConfig(max_failures=3)),
    )
    box = {}

    def _fit():
        box["result"] = trainer.fit()

    t = threading.Thread(target=_fit)
    t.start()
    # Let training make progress (and checkpoint), then yank the side node.
    deadline = _time.monotonic() + 120
    while _time.monotonic() < deadline and not (
            trainer._controller and trainer._controller.metrics_history):
        _time.sleep(0.2)
    assert trainer._controller and trainer._controller.metrics_history, \
        "training never reported"
    cluster.remove_node(side)
    t.join(timeout=300)
    assert not t.is_alive(), "elastic restart did not complete"
    result = box["result"]
    assert result.error is None, result.error
    steps = [m["step"] for m in result.metrics_history]
    assert 7 in steps  # ran to completion after shrinking
