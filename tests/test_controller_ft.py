"""Controller-restart fault tolerance.

Parity target: reference GCS FT — state in Redis
(src/ray/gcs/store_client/redis_store_client.h), raylets tolerate a GCS
restart and re-register (RayletNotifyGCSRestart, core_worker.proto:459).
Here: the controller persists durable domains to disk; standalone node
agents, workers, and drivers reconnect to the restarted controller and
re-assert their state (worker inventory, leases); running work rides
direct connections and finishes through the outage.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu._private import rpc
from ray_tpu._private.ids import NodeID
from ray_tpu._private.resources import ResourceSet


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_head(port, session_dir, persist_dir, session=None):
    env = dict(os.environ)
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    env["RT_CONTROLLER_PERSIST_DIR"] = persist_dir
    cmd = [sys.executable, "-m", "ray_tpu.scripts.head_main",
           "--port", str(port), "--num-cpus", "0",
           "--session-dir", session_dir]
    if session:
        cmd += ["--session", session]
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT)
    deadline = time.monotonic() + 60
    head_json = os.path.join(session_dir, "head.json")
    while time.monotonic() < deadline:
        if os.path.exists(head_json):
            try:
                with open(head_json) as f:
                    info = json.load(f)
                if info.get("pid") == proc.pid:
                    return proc, info
            except Exception:
                pass
        if proc.poll() is not None:
            raise RuntimeError(
                f"head died: {proc.stdout.read().decode()[-2000:]}")
        time.sleep(0.1)
    raise TimeoutError("head did not come up")


def _spawn_agent(controller_addr, session, num_cpus=2):
    env = dict(os.environ)
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    driver_paths = [p for p in sys.path if p and os.path.exists(p)]
    env["PYTHONPATH"] = os.pathsep.join([pkg_root] + driver_paths)
    node_id = NodeID.from_random().hex()
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.node_agent",
         "--controller", controller_addr,
         "--node-id", node_id,
         "--session", session,
         "--resources", json.dumps(ResourceSet({"CPU": float(num_cpus)}).raw())],
        env=env)
    return node_id, proc


def test_controller_restart_running_work_survives(tmp_path):
    """Kill the controller mid-workload; agents/driver reconnect to the
    restarted controller and the workload finishes WITHOUT restarting any
    agent or worker (VERDICT r4 'Done' bar)."""
    port = _free_port()
    session_dir = str(tmp_path / "session")
    persist_dir = str(tmp_path / "persist")
    os.makedirs(session_dir, exist_ok=True)
    head, info = _spawn_head(port, session_dir, persist_dir)
    session = info["session"]
    addr = info["address"]
    agents = [_spawn_agent(addr, session, num_cpus=2) for _ in range(2)]
    try:
        ray_tpu.init(address=addr)

        @ray_tpu.remote
        def slow(i):
            import time as _t

            _t.sleep(6.0)  # long enough to span the controller outage
            return i * 10

        @ray_tpu.remote(max_restarts=0)
        class Counter:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
                return self.n

        c = Counter.remote()
        assert ray_tpu.get(c.bump.remote(), timeout=60) == 1

        # In-flight lease-path tasks that will still be running when the
        # controller dies.
        inflight = [slow.remote(i) for i in range(4)]
        time.sleep(1.0)  # ensure they are dispatched to leased workers

        # ---- kill the controller (hard)
        head.kill()
        head.wait(timeout=10)
        time.sleep(1.0)

        # ---- restart it: same port, same session, same persist dir
        head, info2 = _spawn_head(port, session_dir, persist_dir,
                                  session=session)
        assert info2["session"] == session

        # In-flight tasks complete (their results ride the direct lease
        # connections; owners resolve without the controller).
        assert ray_tpu.get(inflight, timeout=120) == [0, 10, 20, 30]

        # The actor survived: its worker outlived the restart and calls on
        # the existing pipe keep working; state is intact.
        assert ray_tpu.get(c.bump.remote(), timeout=60) == 2

        # NEW work schedules on the restarted controller (agents
        # re-registered; fresh leases grant).
        @ray_tpu.remote
        def add(a, b):
            return a + b

        assert ray_tpu.get(add.remote(3, 4), timeout=120) == 7

        # The agents were never restarted.
        for _nid, proc in agents:
            assert proc.poll() is None

        # A NEW driver can resolve the surviving actor's state via the
        # restarted controller's rebuilt actor table.
        snap = ray_tpu._private.worker.global_worker().state_snapshot()
        alive_nodes = [n for n in snap["nodes"].values() if n["alive"]]
        assert len(alive_nodes) >= 2
    finally:
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        for _nid, proc in agents:
            try:
                proc.kill()
            except Exception:
                pass
        try:
            head.kill()
        except Exception:
            pass


def test_controller_restart_recreates_lost_detached_actor(tmp_path):
    """A detached actor whose WORKER also died during the outage is
    re-created from the persisted spec by the reconcile sweep."""
    port = _free_port()
    session_dir = str(tmp_path / "session")
    persist_dir = str(tmp_path / "persist")
    os.makedirs(session_dir, exist_ok=True)
    head, info = _spawn_head(port, session_dir, persist_dir)
    session = info["session"]
    addr = info["address"]
    nid, agent = _spawn_agent(addr, session, num_cpus=2)
    try:
        ray_tpu.init(address=addr)

        @ray_tpu.remote(lifetime="detached", name="survivor")
        class KV:
            def __init__(self):
                self.d = {}

            def put(self, k, v):
                self.d[k] = v

            def get(self, k):
                return self.d.get(k)

        kv = KV.remote()
        ray_tpu.get(kv.put.remote("a", 1), timeout=60)
        time.sleep(1.0)  # let the persist loop snapshot the actor spec

        # Kill controller AND the agent hosting the actor: worker dies too.
        head.kill()
        head.wait(timeout=10)
        agent.kill()
        agent.wait(timeout=10)

        head, _info2 = _spawn_head(port, session_dir, persist_dir,
                                   session=session)
        # Fresh agent joins; after the reconcile grace the actor re-creates.
        nid2, agent = _spawn_agent(addr, session, num_cpus=2)
        h = None
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            try:
                h = ray_tpu.get_actor("survivor")
                if ray_tpu.get(h.get.remote("a"), timeout=30) is None:
                    break  # re-created fresh (in-memory state restarts)
            except Exception:
                time.sleep(0.5)
        assert h is not None, "detached actor was not re-created"
        # usable after re-creation
        ray_tpu.get(h.put.remote("b", 2), timeout=30)
        assert ray_tpu.get(h.get.remote("b"), timeout=30) == 2
    finally:
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        try:
            agent.kill()
        except Exception:
            pass
        try:
            head.kill()
        except Exception:
            pass
