"""Compiled dataflow execution plane (README "Compiled graphs").

Pins the four tentpole behaviors of the plane: pipelined execution
(execute() returns a DagRef; multiple invocations in flight, fulfilled in
order), general graph shapes (fan-in/fan-out/multi-output/actor-method),
typed attributed stage failure (DagStageError naming the stage with the
full remote traceback, per-invocation — the pipeline survives), and
device-object edges (large jax.Array stage outputs ride the PR 7 device
plane as ~200B placeholders, byte-identical to the host path when off).

reference tests: python/ray/dag/tests/experimental/test_accelerated_dag.py
+ test_torch_tensor_dag.py (the device-edge analog).
"""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import DagStageError, RayTpuError


def _wait(pred, timeout=30.0, what="condition"):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            last = pred()
            if last:
                return last
        except Exception:
            pass
        time.sleep(0.2)
    raise TimeoutError(f"timed out waiting for {what}")


# ----------------------------------------------------------- pipelining
def test_pipelined_execute_returns_dagrefs_in_flight(ray_start_4cpu):
    """execute() must NOT block for the result: with a slow middle stage,
    many invocations are submitted while earlier ones are still in the
    pipe, and results fulfill in submission order."""
    from ray_tpu.dag import InputNode, compile

    @ray_tpu.remote
    def slow(x):
        time.sleep(0.15)
        return x * 10

    @ray_tpu.remote
    def fast(x):
        return x + 1

    with InputNode() as inp:
        dag = fast.bind(slow.bind(inp))
    cdag = compile(dag)
    try:
        t0 = time.perf_counter()
        refs = [cdag.execute(i, timeout=60) for i in range(6)]
        submit_s = time.perf_counter() - t0
        # At 0.15s/invocation a synchronous execute would take >= 0.9s to
        # submit 6; pipelined submission must be far faster AND leave work
        # genuinely in flight.
        assert submit_s < 0.6, f"submission took {submit_s:.2f}s (not pipelined)"
        assert not refs[-1].done(), "last invocation done at submit time?"
        assert [r.get(timeout=60) for r in refs] == [
            i * 10 + 1 for i in range(6)]
        assert all(r.done() for r in refs)
    finally:
        cdag.teardown()


def test_max_inflight_bounds_submission(ray_start_2cpu, monkeypatch):
    """RT_DAG_MAX_INFLIGHT bounds unfulfilled invocations: with the bound
    at 2 and a stage holding results back, the third execute() parks and
    times out; draining the pipe unblocks submission."""
    monkeypatch.setenv("RT_DAG_MAX_INFLIGHT", "2")
    from ray_tpu.dag import InputNode, compile
    from ray_tpu.exceptions import GetTimeoutError

    @ray_tpu.remote
    def slow(x):
        time.sleep(0.4)
        return x

    with InputNode() as inp:
        dag = slow.bind(inp)
    cdag = compile(dag)
    try:
        r0 = cdag.execute(0)
        r1 = cdag.execute(1)
        with pytest.raises(GetTimeoutError, match="in flight"):
            cdag.execute(2, timeout=0.05)
        assert r0.get(timeout=30) == 0 and r1.get(timeout=30) == 1
        # Fulfilled results release the window.
        assert cdag.execute(3).get(timeout=30) == 3
    finally:
        cdag.teardown()


# ------------------------------------------------------------- graph shapes
def test_fan_in_fan_out_multi_output_actor_method(ray_start_4cpu):
    """One graph exercising every shape at once: an EXISTING actor's
    method stage fans out to a function join (fan-in) and a second output
    (multi-output), with a literal kwarg riding a stage."""
    from ray_tpu.dag import InputNode, MultiOutputNode, compile

    @ray_tpu.remote
    class Scaler:
        def __init__(self, k):
            self.k = k
            self.calls = 0

        def scale(self, x):
            self.calls += 1
            return x * self.k

        def count(self):
            return self.calls

    @ray_tpu.remote
    def inc(x, by=1):
        return x + by

    @ray_tpu.remote
    def join(a, b):
        return (a, b)

    actor = Scaler.remote(10)
    with InputNode() as inp:
        s = actor.scale.bind(inp)           # actor-method stage, fanned out
        i = inc.bind(inp, by=5)             # literal kwarg
        dag = MultiOutputNode([join.bind(s, i), inc.bind(s)])
    cdag = compile(dag)
    try:
        for x in (1, 3, 7):
            j, k = cdag.execute(x).get(timeout=60)
            assert j == (10 * x, x + 5)
            assert k == 10 * x + 1
        # The actor advanced real state and still serves normal calls.
        assert ray_tpu.get(actor.count.remote(), timeout=30) == 3
    finally:
        cdag.teardown()
    # The user actor survives teardown (only its loop thread stopped).
    assert ray_tpu.get(actor.count.remote(), timeout=30) == 3


# ------------------------------------------------------- attributed errors
def test_diamond_error_names_stage_and_carries_traceback(ray_start_4cpu):
    """A stage exception propagates through a diamond to the output as a
    TYPED DagStageError naming the failing stage with the full remote
    traceback — and only poisons ITS invocation; the pipeline keeps
    flowing for the next one."""
    from ray_tpu.dag import InputNode, compile

    @ray_tpu.remote
    def src(x):
        return x

    @ray_tpu.remote
    def left(x):
        if x == 13:
            raise ValueError("kaput-13")
        return x * 2

    @ray_tpu.remote
    def right(x):
        return x + 1

    @ray_tpu.remote
    def merge(a, b):
        return a + b

    with InputNode() as inp:
        s = src.bind(inp)
        dag = merge.bind(left.bind(s), right.bind(s))
    cdag = compile(dag)
    try:
        assert cdag.execute(2).get(timeout=60) == 2 * 2 + 3
        with pytest.raises(DagStageError) as ei:
            cdag.execute(13).get(timeout=60)
        e = ei.value
        assert isinstance(e, RayTpuError)          # taxonomy-compliant
        assert e.stage and "left" in e.stage       # names the stage
        assert e.invocation == 1                   # names the invocation
        # Satellite pin: the FULL formatted remote traceback rides along,
        # not just repr(e).
        assert e.traceback_str and "Traceback" in e.traceback_str
        assert 'raise ValueError("kaput-13")' in e.traceback_str
        assert "kaput-13" in str(e)
        # Per-invocation failure: the graph is still healthy.
        assert cdag.execute(4).get(timeout=60) == 4 * 2 + 5
    finally:
        cdag.teardown()


# ----------------------------------------------------------- teardown/leaks
def test_teardown_unlinks_every_channel(ray_start_2cpu):
    """Kill-then-unlink: teardown leaves NO rtch_* shm segment behind,
    including after a stage error mid-run (the loops that saw the error
    keep consuming — nothing wedges the stop tokens)."""
    from ray_tpu.dag import InputNode, compile

    @ray_tpu.remote
    def maybe_boom(x):
        if x < 0:
            raise RuntimeError("negative")
        return x

    with InputNode() as inp:
        dag = maybe_boom.bind(inp)
    cdag = compile(dag)
    paths = [ch._path for ch in cdag._channels]
    assert paths and all(os.path.exists(p) for p in paths)
    with pytest.raises(DagStageError):
        cdag.execute(-1).get(timeout=60)
    assert cdag.execute(5).get(timeout=60) == 5
    cdag.teardown()
    leaked = [p for p in paths if os.path.exists(p)]
    assert not leaked, f"teardown leaked shm channels: {leaked}"
    # Idempotent.
    cdag.teardown()
    with pytest.raises(RuntimeError, match="torn down"):
        cdag.execute(1)


def test_oversized_input_fails_attributed_not_hang(ray_start_2cpu):
    """A value bigger than the edge capacity must fail the invocation with
    a typed error, not silently strand the already-returned DagRef."""
    from ray_tpu.dag import InputNode, compile

    @ray_tpu.remote
    def f(x):
        return len(x)

    with InputNode() as inp:
        dag = f.bind(inp)
    cdag = compile(dag, channel_size=4096)
    try:
        ref = cdag.execute(b"x" * 65536)
        with pytest.raises(DagStageError, match="submission failed"):
            ref.get(timeout=30)
    finally:
        cdag.teardown()


# -------------------------------------------------------- device-object edges
def _device_edge_graph():
    import jax.numpy as jnp

    n = 1 << 16  # 256KB float32: past RT_DEVICE_OBJECT_MIN_BYTES

    @ray_tpu.remote
    def produce(x):
        return jnp.full((n,), float(x), jnp.float32)

    @ray_tpu.remote
    def transform(a):
        return a * 2.0 + 1.0

    from ray_tpu.dag import InputNode, compile

    with InputNode() as inp:
        dag = transform.bind(produce.bind(inp))
    return compile(dag)


def _run_device_edge_dag(cdag, xs):
    outs = []
    for x in xs:
        arr = cdag.execute(x).get(timeout=120)
        outs.append(np.asarray(arr))
    return outs


def test_device_edges_on_off_byte_equivalence(shutdown_only, device_plane_cpu,
                                              monkeypatch):
    """The SAME graph over large jax.Array edges produces byte-identical
    results with device edges on (placeholders + tier-ladder resolve) and
    off (RT_DAG_DEVICE_EDGES=0: full pickles through the shm ring) — and
    the on path actually pins (the channel carried the ~200B ref)."""
    xs = [1, 2, 3, 4]
    ray_tpu.init(num_cpus=4)
    cdag = _device_edge_graph()
    try:
        on_outs = _run_device_edge_dag(cdag, xs)
        # The producing stage holds pins (2-invocation retention window).
        pins = sum(ray_tpu.get(a.probe.remote(), timeout=30)["count"]
                   for a in cdag._actors)
        assert pins > 0, "device edges on but no stage pinned anything"
    finally:
        cdag.teardown()
    ray_tpu.shutdown()

    monkeypatch.setenv("RT_DAG_DEVICE_EDGES", "0")
    ray_tpu.init(num_cpus=4)
    cdag = _device_edge_graph()
    try:
        off_outs = _run_device_edge_dag(cdag, xs)
        pins = sum(ray_tpu.get(a.probe.remote(), timeout=30)["count"]
                   for a in cdag._actors)
        assert pins == 0, "RT_DAG_DEVICE_EDGES=0 but a stage pinned"
    finally:
        cdag.teardown()
    for on, off in zip(on_outs, off_outs):
        assert on.dtype == off.dtype and on.shape == off.shape
        assert np.array_equal(on, off)


def test_device_edge_pins_retire_no_leak(ray_start_2cpu, device_plane_cpu):
    """Steady-state churn must NOT accrete one pinned array per
    invocation: the 2-invocation retention window bounds producer-side
    residency."""
    cdag = _device_edge_graph()
    try:
        for x in range(12):
            cdag.execute(x).get(timeout=120)
        stats = [ray_tpu.get(a.probe.remote(), timeout=30)
                 for a in cdag._actors]
        worst = max(s["count"] for s in stats)
        assert worst <= 2, f"pins accreted past the retention window: {stats}"
    finally:
        cdag.teardown()


# ------------------------------------------------------------ observability
def test_dag_events_compiled_and_teardown(ray_start_2cpu):
    """dag_compiled / dag_teardown land in the PR 14 event plane, entity-
    indexed by the dag id."""
    from ray_tpu.dag import InputNode, compile
    from ray_tpu.util import state

    @ray_tpu.remote
    def f(x):
        return x

    with InputNode() as inp:
        dag = f.bind(inp)
    cdag = compile(dag)
    dag_id = cdag.dag_id
    assert cdag.execute(1).get(timeout=60) == 1
    cdag.teardown()

    def _events():
        rows = state.list_events(entity=dag_id)
        kinds = {e["kind"] for e in rows}
        if {"dag_compiled", "dag_teardown"} <= kinds:
            return rows
        return None

    rows = _wait(_events, what="dag lifecycle events")
    comp = next(e for e in rows if e["kind"] == "dag_compiled")
    assert comp["attrs"]["stages"] == 1
    td = next(e for e in rows if e["kind"] == "dag_teardown")
    assert td["attrs"]["clean"] is True


def test_dag_invocation_spans_when_sampled(shutdown_only, monkeypatch):
    """A sampled invocation records a dag.execute root with per-stage
    dag.stage children under the PR 11 tracing plane."""
    monkeypatch.setenv("RT_TRACING", "1")
    ray_tpu.init(num_cpus=4)
    from ray_tpu.dag import InputNode, compile
    from ray_tpu.util import state

    @ray_tpu.remote
    def a(x):
        return x + 1

    @ray_tpu.remote
    def b(x):
        return x * 2

    with InputNode() as inp:
        dag = b.bind(a.bind(inp))
    cdag = compile(dag)
    try:
        assert cdag.execute(3).get(timeout=60) == 8
    finally:
        cdag.teardown()

    def _spans():
        for row in state.list_traces(limit=1000):
            doc = state.get_trace(row["trace_id"])
            spans = doc.get("spans", [])
            names = [s.get("n") for s in spans]
            if "dag.execute" not in names:
                continue
            stages = [s for s in spans if s.get("n") == "dag.stage"]
            if len(stages) >= 2:
                root = next(s for s in spans if s.get("n") == "dag.execute")
                # Stage spans parent to the execute span (causal chain).
                if all(s.get("p") == root.get("s") for s in stages):
                    return spans
        return None

    _wait(_spans, what="dag.execute -> dag.stage span chain")
