"""Chaos coverage for the telemetry & profiling plane (README "Telemetry
& profiling"): a severed dashboard->controller connection recovers on the
next poll (no dashboard bounce), agent death leaves no stuck series (they
age out of the controller ring and `ray-tpu top` marks the node DEAD
rather than freezing last values), worker death purges that worker's
series immediately, and profiling a worker that dies mid-capture returns
an attributed error instead of hanging.
"""

import threading
import time

import pytest

import ray_tpu
from ray_tpu._private import rpc
from ray_tpu.util import state


def test_dashboard_recovers_from_severed_controller_conn(ray_start_2cpu):
    """Sever the dashboard's controller connection mid-poll: the next tick
    must recover through the retry/reconnect path — before PR 12 a
    controller-side conn loss could 500 every panel until the dashboard
    process was bounced."""
    import urllib.request

    from ray_tpu.dashboard import start_dashboard

    d = start_dashboard(port=0)
    try:
        def get_nodes():
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{d.port}/api/nodes", timeout=10) as r:
                assert r.status == 200
                return r.read()

        assert b"node_id" in get_nodes()
        for _ in range(3):  # sever repeatedly; every next poll must recover
            conn = d._conn
            assert conn is not None
            rpc.FaultInjector.sever_conn(conn)
            deadline = time.monotonic() + 5
            while not conn.closed and time.monotonic() < deadline:
                time.sleep(0.02)
            assert b"node_id" in get_nodes()
    finally:
        d.stop()


def test_agent_death_ages_out_series_and_top_marks_dead(monkeypatch):
    """Kill a node's agent mid-sampling: its series stop arriving, age out
    of the controller ring after RT_TELEMETRY_WINDOW_S, and the top
    renderer shows the node DEAD instead of freezing its last values."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.scripts.cli import _top_lines

    monkeypatch.setenv("RT_TELEMETRY_INTERVAL_S", "0.2")
    monkeypatch.setenv("RT_TELEMETRY_WINDOW_S", "3")
    cluster = Cluster(head_node_args={"num_cpus": 1})
    try:
        n2 = cluster.add_node(num_cpus=1)
        ray_tpu.init(address=cluster.address)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with_series = {r["node_id"] for r in state.timeseries(
                series="node.cpu")}
            if n2.node_id in with_series:
                break
            time.sleep(0.3)
        assert n2.node_id in with_series, "second node never sampled"

        cluster.remove_node(n2)  # SIGKILL: death mid-sample
        # The ring must drain the dead node's series within the window
        # (+ prune cadence slack); the surviving node keeps sampling.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            rows = state.timeseries(node_id=n2.node_id)
            if not rows:
                break
            time.sleep(0.5)
        assert state.timeseries(node_id=n2.node_id) == [], (
            "dead node's series never aged out")
        assert state.timeseries(series="node.cpu"), (
            "survivor's series vanished too")

        u = state.cluster_utilization()
        dead = u["nodes"][n2.node_id]
        assert not dead["alive"]
        rendered = "\n".join(_top_lines(u))
        assert f"{n2.node_id[:8]:<10} DEAD" in rendered.replace(
            "DEAD    ", "DEAD"), rendered
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_worker_death_purges_its_series(monkeypatch, shutdown_only):
    """Kill a worker mid-sampling: its worker-scoped rings are purged from
    the controller immediately (not after the 600s window prune), so
    cluster_utilization / `ray-tpu top` stop reporting the dead worker's
    last RSS/CPU sample as current."""
    monkeypatch.setenv("RT_TELEMETRY_INTERVAL_S", "0.2")
    ray_tpu.init(num_cpus=2)

    @ray_tpu.remote(max_restarts=0)
    class Busy:
        def spin(self, seconds):
            t0 = time.time()
            while time.time() - t0 < seconds:
                pass
            return 1

    a = Busy.remote()
    ref = a.spin.remote(30.0)
    w = ray_tpu._private.worker.global_worker()
    info = w.io.run(w.controller.call(
        "get_actor_info", actor_id=a._actor_id, wait=True))
    sub = info["worker_id"][:12]

    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if any(r["worker_id"] == sub for r in state.timeseries()):
            break
        time.sleep(0.3)
    assert any(r["worker_id"] == sub for r in state.timeseries()), (
        "actor worker never sampled")

    ray_tpu.kill(a)
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if not any(r["worker_id"] == sub for r in state.timeseries()):
            break
        time.sleep(0.3)
    assert not any(r["worker_id"] == sub for r in state.timeseries()), (
        "dead worker's series were not purged")
    workers = {wid for n in state.cluster_utilization()["nodes"].values()
               for wid in (n.get("workers") or {})}
    assert sub not in workers
    del ref


def test_profile_worker_death_mid_capture_attributed(ray_start_2cpu):
    """Kill the worker while an 8s capture is in flight: the call returns
    an attributed error well before the capture window would end — never
    a hang, never a success."""

    @ray_tpu.remote(max_restarts=0)
    class Busy:
        def spin(self, seconds):
            t0 = time.time()
            while time.time() - t0 < seconds:
                pass
            return 1

    a = Busy.remote()
    ref = a.spin.remote(30.0)
    time.sleep(0.5)
    w = ray_tpu._private.worker.global_worker()
    info = w.io.run(w.controller.call(
        "get_actor_info", actor_id=a._actor_id, wait=True))

    result = {}

    def capture():
        result["rep"] = w.io.run(w.controller.call(
            "profile_worker", worker_id=info["worker_id"], seconds=8.0,
            mode="cpu"), timeout=60)

    t0 = time.monotonic()
    th = threading.Thread(target=capture, daemon=True)
    th.start()
    time.sleep(1.0)  # capture is mid-window
    ray_tpu.kill(a)
    th.join(timeout=20)
    elapsed = time.monotonic() - t0
    assert not th.is_alive(), "profile capture hung after worker death"
    rep = result["rep"]
    assert rep["found"] is False, rep
    assert "mid-capture" in rep["error"] or "not alive" in rep["error"], rep
    assert elapsed < 8.0, (
        f"capture should abort on death, not run out the window "
        f"({elapsed:.1f}s)")
    del ref
