"""Objects: put/get/wait/free (parity: reference test_object_store / test_wait)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import GetTimeoutError


def test_put_get_roundtrip(ray_start_2cpu):
    for v in [1, "s", None, {"a": [1, 2]}, (1, 2), {1, 2}, b"bytes", 1.5]:
        assert ray_tpu.get(ray_tpu.put(v), timeout=30) == v


def test_put_numpy_zero_copy(ray_start_2cpu):
    arr = np.arange(1_000_000, dtype=np.float32)
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref, timeout=30)
    np.testing.assert_array_equal(out, arr)
    out2 = ray_tpu.get(ref, timeout=30)
    np.testing.assert_array_equal(out2, arr)


def test_put_on_ref_rejected(ray_start_2cpu):
    r = ray_tpu.put(1)
    with pytest.raises(TypeError):
        ray_tpu.put(r)


def test_get_timeout(ray_start_2cpu):
    @ray_tpu.remote
    def slow():
        time.sleep(5)
        return 1

    ref = slow.remote()
    with pytest.raises(GetTimeoutError):
        ray_tpu.get(ref, timeout=0.2)


def test_wait_basic(ray_start_2cpu):
    @ray_tpu.remote
    def quick(i):
        return i

    @ray_tpu.remote
    def slow():
        time.sleep(10)
        return -1

    refs = [quick.remote(0), quick.remote(1), slow.remote()]
    ready, pending = ray_tpu.wait(refs, num_returns=2, timeout=30)
    assert len(ready) == 2 and len(pending) == 1
    assert set(ray_tpu.get(ready, timeout=30)) == {0, 1}


def test_wait_timeout(ray_start_2cpu):
    @ray_tpu.remote
    def slow():
        time.sleep(10)

    ready, pending = ray_tpu.wait([slow.remote()], timeout=0.3)
    assert ready == []
    assert len(pending) == 1


def test_shared_object_many_readers(ray_start_2cpu):
    arr = np.ones(300_000, dtype=np.float64)
    ref = ray_tpu.put(arr)

    @ray_tpu.remote
    def total(a):
        return float(a.sum())

    refs = [total.remote(ref) for _ in range(4)]
    assert ray_tpu.get(refs, timeout=60) == [300_000.0] * 4


def test_cluster_resources_api(ray_start_2cpu):
    total = ray_tpu.cluster_resources()
    assert total["CPU"] == 2.0
    assert ray_tpu.available_resources()["CPU"] <= 2.0
    assert len(ray_tpu.nodes()) == 1


def test_returned_borrowed_ref_resolves(ray_start_2cpu):
    """A small inline ref created by one actor and RETURNED (not gotten) by
    its owner to the driver must resolve for the borrower — the owner
    advertises owned refs when they escape inside a return value."""

    @ray_tpu.remote
    class Maker:
        def make(self, v):
            return v * 2

    @ray_tpu.remote
    class Owner:
        def __init__(self):
            self.maker = Maker.remote()

        def indirect(self, v):
            # returns the REF itself; the driver becomes a borrower
            return self.maker.make.remote(v)

    owner = Owner.remote()
    inner_ref = ray_tpu.get(owner.indirect.remote(21), timeout=60)
    assert ray_tpu.get(inner_ref, timeout=30) == 42


def test_chunked_cross_node_fetch(ray_start_cluster, tmp_path):
    """A multi-chunk object fetched across nodes arrives intact (chunked
    transfer + admission control; reference object_manager Push/Pull,
    pull_manager.h admission). The side node gets its own shm dir so the
    same-host /dev/shm attach shortcut cannot serve the object — the fetch
    MUST take the remote chunked path."""
    import numpy as np

    cluster = ray_start_cluster
    side_shm = str(tmp_path / "side_shm")
    import os as _os

    _os.makedirs(side_shm, exist_ok=True)
    cluster.add_node(num_cpus=1, resources={"side": 1},
                     env={"RT_SHM_DIR": side_shm})
    ray_tpu.init(address=cluster.address,
                 _system_config={"object_chunk_bytes": 1 * 1024 * 1024})

    rng = np.random.default_rng(7)
    arr = rng.integers(0, 256, size=7 * 1024 * 1024 + 123, dtype=np.uint8)
    ref = ray_tpu.put(arr)  # > 7 chunks at the 1 MiB test chunk size

    @ray_tpu.remote(resources={"side": 1})
    def digest(a):
        import hashlib

        return hashlib.sha1(a.tobytes()).hexdigest()

    import hashlib

    expect = hashlib.sha1(arr.tobytes()).hexdigest()
    assert ray_tpu.get(digest.remote(ref), timeout=120) == expect
