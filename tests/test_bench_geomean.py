"""Bench report hardening (ISSUE 19 satellite): the geomean-vs-baseline
input is computed by a real helper with a pinned contract — a lane that
cannot produce a trustworthy number reports a {"fallback": true} detail
INSTEAD of a result, and any non-positive value that slips into results
anyway (a lane bug, e.g. a negative TFLOP/s from a non-monotonic timing
window) is EXCLUDED from the ratio set, never clamped into a near-zero
log-ratio that drags vs_baseline to the floor."""

import math

import pytest

import bench


def test_baseline_ratios_basic():
    ratios = bench._baseline_ratios(
        {"a": 500.0, "b": 2000.0}, {"a": 1000.0, "b": 1000.0})
    assert ratios == {"a": 0.5, "b": 2.0}


def test_baseline_ratios_ignores_metrics_without_baseline():
    # Extra result keys (TPU lanes, detail-only rates) never enter the
    # geomean: only baselined metrics are ratio inputs.
    ratios = bench._baseline_ratios(
        {"a": 1000.0, "flash_attention_tflops": 120.0}, {"a": 1000.0})
    assert ratios == {"a": 1.0}


def test_baseline_ratios_excludes_non_positive_lane_values():
    # The BENCH_r05 regression shape: a broken timing window produced
    # -49.6 "TFLOP/s". Under the old max(r, 1e-9) clamp a single such
    # lane contributed log(1e-9) and cratered the geomean; the contract
    # is exclusion, so the healthy lanes fully determine the mean.
    ratios = bench._baseline_ratios(
        {"a": 1000.0, "bad": -49.6, "zero": 0.0},
        {"a": 1000.0, "bad": 100.0, "zero": 100.0})
    assert ratios == {"a": 1.0}
    assert bench._ratio_geomean(ratios) == pytest.approx(1.0)


def test_ratio_geomean_matches_log_mean_and_empty_is_neutral():
    ratios = {"a": 0.5, "b": 2.0, "c": 1.0}
    expect = math.exp(sum(math.log(r) for r in ratios.values()) / 3)
    assert bench._ratio_geomean(ratios) == pytest.approx(expect)
    assert bench._ratio_geomean({}) == 1.0
