"""Scale-envelope stress tier — the repo's miniature of the reference's
release/benchmarks/README.md:8-11 scalability envelope (2,000 nodes /
40k actors / 10k tasks / 1k PGs on a cloud fleet), scaled to a CI box:
16 simulated nodes, 1,000 concurrent tasks, a (host-sized) actor wave,
50 placement groups, with scheduler-responsiveness bounds asserted
throughout — surfacing central-controller limits before they become
architecture (VERDICT r4 item 10).

N_ACTORS is bounded by raw process-spawn throughput (one dedicated
process per actor; a 1-core CI box does ~0.5 spawn/s under 16 agents) —
RT_SCALE_N_ACTORS raises it on real multi-core hosts.
"""

import os
import time

N_ACTORS = int(os.environ.get("RT_SCALE_N_ACTORS", "64"))

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def scale_cluster():
    cluster = Cluster(head_node_args={"num_cpus": 0})
    # 16 simulated nodes (real NodeAgent subprocesses, declared resources).
    for _ in range(15):
        cluster.add_node(num_cpus=1, resources={"slot": 16})
    cluster.add_node(num_cpus=1, resources={"slot": 16})
    ray_tpu.init(address=cluster.address)
    cluster.wait_for_nodes()
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


def _controller_latency() -> float:
    w = ray_tpu._private.worker.global_worker()
    t0 = time.monotonic()
    w.state_snapshot()
    return time.monotonic() - t0


def test_sixteen_nodes_alive(scale_cluster):
    snap = ray_tpu._private.worker.global_worker().state_snapshot()
    alive = [n for n in snap["nodes"].values() if n["alive"]]
    assert len(alive) >= 16  # 16 workers (+ the 0-cpu head)


def test_thousand_concurrent_tasks(scale_cluster):
    """1,000 tasks submitted at once across 16 nodes: all complete, the
    controller stays responsive under the queue."""

    @ray_tpu.remote
    def work(i):
        return i * 3

    t0 = time.monotonic()
    refs = [work.remote(i) for i in range(1000)]
    submit_s = time.monotonic() - t0
    # controller responsiveness mid-flood
    lat = _controller_latency()
    out = ray_tpu.get(refs, timeout=300)
    total_s = time.monotonic() - t0
    assert out == [i * 3 for i in range(1000)]
    assert submit_s < 20.0, f"submission took {submit_s:.1f}s"
    assert lat < 2.0, f"controller latency {lat:.2f}s under task flood"
    assert total_s < 180.0, f"1k tasks took {total_s:.1f}s"
    rate = 1000 / total_s
    print(f"\n  1k tasks: {total_s:.1f}s ({rate:,.0f} tasks/s), "
          f"submit {submit_s:.2f}s, controller latency {lat*1000:.0f}ms")


def test_actor_wave(scale_cluster):
    """N live actors (dedicated processes across the 16 nodes): create,
    fan a call over every one, kill. The controller's actor table and the
    driver's N concurrent actor pipes must hold up."""

    @ray_tpu.remote(num_cpus=0)
    class A:
        def __init__(self, i):
            self.i = i

        def who(self):
            return self.i

    t0 = time.monotonic()
    actors = [A.remote(i) for i in range(N_ACTORS)]
    # fan one call across all 200 (forces every creation to finish)
    vals = ray_tpu.get([a.who.remote() for a in actors], timeout=600)
    create_s = time.monotonic() - t0
    assert vals == list(range(N_ACTORS))
    lat = _controller_latency()
    assert lat < 2.0, f"controller latency {lat:.2f}s with {N_ACTORS} actors"
    # second fan-out exercises 200 warm pipes
    t1 = time.monotonic()
    vals = ray_tpu.get([a.who.remote() for a in actors], timeout=120)
    warm_s = time.monotonic() - t1
    assert vals == list(range(N_ACTORS))
    assert warm_s < 30.0, f"warm {N_ACTORS}-actor fanout took {warm_s:.1f}s"
    for a in actors:
        ray_tpu.kill(a)
    print(f"\n  {N_ACTORS} actors: create+first-call {create_s:.1f}s "
          f"({N_ACTORS/create_s:.1f}/s), warm fanout {warm_s:.2f}s")


def test_fifty_placement_groups(scale_cluster):
    """50 PGs (2 bundles each) prepared/committed across 16 nodes, tasks
    scheduled into a few of them, then all removed — bundle accounting
    must return to clean."""
    from ray_tpu.util.placement_group import placement_group, remove_placement_group

    t0 = time.monotonic()
    pgs = [placement_group([{"slot": 1}, {"slot": 1}], strategy="PACK")
           for _ in range(50)]
    for pg in pgs:
        ray_tpu.get(pg.ready(), timeout=120)
    create_s = time.monotonic() - t0
    assert create_s < 150.0, f"50 PGs took {create_s:.1f}s"

    @ray_tpu.remote(num_cpus=0, resources={"slot": 1})
    def in_pg():
        return "ok"

    outs = ray_tpu.get(
        [in_pg.options(placement_group=pgs[i]).remote() for i in range(5)],
        timeout=120)
    assert outs == ["ok"] * 5
    for pg in pgs:
        remove_placement_group(pg)
    # all bundle reservations released
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        avail = ray_tpu.available_resources()
        if avail.get("slot", 0) >= 16 * 16:
            break
        time.sleep(0.25)
    assert ray_tpu.available_resources().get("slot", 0) >= 16 * 16
    print(f"\n  50 PGs: create {create_s:.1f}s ({50/create_s:.1f}/s)")
