"""util extras: ActorPool, Queue, multiprocessing.Pool, air.session.

reference tests: python/ray/tests/test_actor_pool.py, test_queue.py,
test_multiprocessing.py.
"""

import time

import pytest

import ray_tpu
from ray_tpu.util import ActorPool, Empty, Queue


def test_actor_pool_map_ordered_and_unordered(ray_start_2cpu):
    @ray_tpu.remote
    class Sq:
        def sq(self, x):
            return x * x

    actors = [Sq.remote() for _ in range(2)]
    pool = ActorPool(actors)
    assert list(pool.map(lambda a, v: a.sq.remote(v), range(8))) == [
        i * i for i in range(8)]
    out = sorted(pool.map_unordered(lambda a, v: a.sq.remote(v), range(8)))
    assert out == sorted(i * i for i in range(8))
    # submit/get_next interleave; more submits than actors queues work
    for i in range(5):
        pool.submit(lambda a, v: a.sq.remote(v), i)
    got = [pool.get_next(timeout=60) for _ in range(5)]
    assert got == [0, 1, 4, 9, 16]


def test_queue_basic_and_cross_actor(ray_start_2cpu):
    q = Queue(maxsize=4)
    q.put("a")
    q.put("b")
    assert q.qsize() == 2
    assert q.get() == "a"
    with pytest.raises(Empty):
        q.get_nowait() and q.get_nowait()  # only one item left
        q.get_nowait()

    # a worker task produces through the SAME queue (handle pickles)
    @ray_tpu.remote
    def producer(q, n):
        for i in range(n):
            q.put(i)
        return True

    ref = producer.remote(q, 3)
    got = [q.get(timeout=30) for _ in range(3)]
    assert got == [0, 1, 2]
    assert ray_tpu.get(ref, timeout=60) is True
    # blocking get times out cleanly
    with pytest.raises(Empty):
        q.get(timeout=0.2)
    q.shutdown()


def test_multiprocessing_pool(ray_start_2cpu):
    from ray_tpu.util.multiprocessing import Pool

    def cube(x):
        return x ** 3

    def add(a, b):
        return a + b

    with Pool() as p:
        assert p.map(cube, range(6)) == [i ** 3 for i in range(6)]
        assert p.starmap(add, [(1, 2), (3, 4)]) == [3, 7]
        ar = p.apply_async(cube, (5,))
        assert ar.get(timeout=60) == 125
        assert sorted(p.imap_unordered(cube, range(4))) == [0, 1, 8, 27]


def test_air_session_in_trainer(ray_start_2cpu, tmp_path):
    from ray_tpu.air import session
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    def loop(config):
        session.report({"rank": session.get_world_rank(),
                        "world": session.get_world_size()})

    res = JaxTrainer(
        loop, scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path))).fit()
    assert res.error is None
    assert res.metrics["world"] == 2
