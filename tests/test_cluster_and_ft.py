"""Multi-node scheduling + fault tolerance.

Parity: reference tests test_multi_node*.py, test_actor_failures.py,
test_reconstruction*.py — run against the one-machine Cluster fixture
(reference cluster_utils.Cluster:135)."""

import time

import pytest

import ray_tpu
from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy


def _node_of_task():
    import os

    return os.environ.get("RT_NODE_ID")


def test_two_nodes_spread(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address)
    assert ray_tpu.cluster_resources()["CPU"] == 3.0

    @ray_tpu.remote(scheduling_strategy="SPREAD")
    def where():
        import os

        return os.environ.get("RT_NODE_ID")

    nodes = set(ray_tpu.get([where.remote() for _ in range(6)], timeout=120))
    assert len(nodes) == 2


def test_node_affinity(ray_start_cluster):
    cluster = ray_start_cluster
    n2 = cluster.add_node(num_cpus=1)
    ray_tpu.init(address=cluster.address)

    @ray_tpu.remote
    def where():
        import os

        return os.environ.get("RT_NODE_ID")

    strat = NodeAffinitySchedulingStrategy(node_id=n2.node_id)
    got = ray_tpu.get(where.options(scheduling_strategy=strat).remote(), timeout=60)
    assert got == n2.node_id


def test_task_retry_on_node_death(ray_start_cluster):
    cluster = ray_start_cluster
    n2 = cluster.add_node(num_cpus=1)
    ray_tpu.init(address=cluster.address)

    @ray_tpu.remote(max_retries=3)
    def slow_then_value():
        import time

        time.sleep(3)
        return "survived"

    strat = NodeAffinitySchedulingStrategy(node_id=n2.node_id, soft=True)
    ref = slow_then_value.options(scheduling_strategy=strat).remote()
    time.sleep(0.8)  # let it start on n2
    cluster.remove_node(n2)  # kill the node mid-task
    assert ray_tpu.get(ref, timeout=120) == "survived"


def test_actor_restart_on_node_death(ray_start_cluster):
    cluster = ray_start_cluster
    n2 = cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address)

    @ray_tpu.remote(max_restarts=-1, max_task_retries=-1)
    class Pinger:
        def node(self):
            import os

            return os.environ.get("RT_NODE_ID")

    strat = NodeAffinitySchedulingStrategy(node_id=n2.node_id, soft=True)
    p = Pinger.options(scheduling_strategy=strat, max_restarts=2, max_task_retries=2).remote()
    assert ray_tpu.get(p.node.remote(), timeout=60) == n2.node_id
    cluster.remove_node(n2)
    # Actor restarts on the remaining (head) node.
    got = ray_tpu.get(p.node.remote(), timeout=120)
    assert got is not None and got != n2.node_id


def test_placement_group_pack_and_task(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address)
    from ray_tpu.util.placement_group import placement_group, remove_placement_group

    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.wait(timeout_seconds=30)

    @ray_tpu.remote(num_cpus=1, placement_group=pg)
    def inside():
        import os

        return os.environ.get("RT_NODE_ID")

    n = ray_tpu.get(inside.remote(), timeout=60)
    assert n is not None
    remove_placement_group(pg)


def test_placement_group_strict_spread(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1)
    cluster.add_node(num_cpus=1)
    ray_tpu.init(address=cluster.address)
    from ray_tpu.util.placement_group import placement_group

    pg = placement_group([{"CPU": 1}] * 3, strategy="STRICT_SPREAD")
    assert pg.wait(timeout_seconds=30)

    @ray_tpu.remote(num_cpus=1, placement_group=pg)
    def where():
        import os

        return os.environ.get("RT_NODE_ID")

    nodes = ray_tpu.get([where.options(placement_group_bundle_index=i).remote() for i in range(3)], timeout=120)
    assert len(set(nodes)) == 3


def test_infeasible_pg_pending(ray_start_cluster):
    ray_tpu.init(address=ray_start_cluster.address)
    from ray_tpu.util.placement_group import placement_group

    pg = placement_group([{"CPU": 99}], strategy="PACK")
    assert not pg.wait(timeout_seconds=0.5)


def test_controller_persistence_restart(shutdown_only, tmp_path):
    """KV contents and named actors survive a full controller restart: the
    new controller restores its snapshot and re-creates the actor from its
    persisted spec once the node joins (reference GCS+Redis restart,
    redis_store_client.h — our agents share fate with the controller, so
    re-creation rather than adoption is the contract)."""
    persist = str(tmp_path / "ctrl")

    ray_tpu.init(num_cpus=2, _system_config={"controller_persist_dir": persist})

    @ray_tpu.remote
    class Registry:
        def __init__(self):
            self.greeting = "hello-from-v1"

        def greet(self):
            return self.greeting

    reg = Registry.options(name="registry", lifetime="detached").remote()
    assert ray_tpu.get(reg.greet.remote(), timeout=60) == "hello-from-v1"
    from ray_tpu._private.worker import global_worker

    global_worker().kv("put", ns="app", key="cfg", value=b"v42")
    ray_tpu.shutdown()  # stop() flushes dirty state before exiting

    # Fresh cluster, same persist dir: restore.
    ray_tpu.init(num_cpus=2, _system_config={"controller_persist_dir": persist})
    w = global_worker()
    assert w.kv("get", ns="app", key="cfg")["value"] == b"v42"
    reg2 = ray_tpu.get_actor("registry")
    assert ray_tpu.get(reg2.greet.remote(), timeout=120) == "hello-from-v1"
