"""Workflow durability, compiled-DAG shm channels, LLM batch + serve.

reference tests: python/ray/workflow/tests/test_basic_workflows.py,
python/ray/dag/tests/experimental/test_accelerated_dag.py,
python/ray/llm/tests/.
"""

import json
import os
import socket
import time
import urllib.request

import numpy as np
import pytest

import ray_tpu


def test_workflow_run_and_resume(ray_start_2cpu, tmp_path):
    from ray_tpu import workflow

    workflow.init(str(tmp_path / "wf"))
    marker = tmp_path / "exec_count"
    marker.write_text("0")

    @ray_tpu.remote
    def double(x, marker_path):
        p = __import__("pathlib").Path(marker_path)
        p.write_text(str(int(p.read_text()) + 1))
        return x * 2

    @ray_tpu.remote
    def add(a, b):
        return a + b

    dag = add.bind(double.bind(3, str(marker)), double.bind(4, str(marker)))
    assert workflow.run(dag, workflow_id="wf1") == 14
    assert marker.read_text() == "2"  # both steps executed

    # Re-run the same workflow: every step memoized, nothing re-executes.
    assert workflow.run(dag, workflow_id="wf1") == 14
    assert marker.read_text() == "2"
    assert workflow.resume("wf1") == 14
    st = workflow.get_status("wf1")
    assert st["status"] == "SUCCESSFUL" and st["skipped"] == 3

    # A different workflow id re-executes.
    assert workflow.run(dag, workflow_id="wf2") == 14
    assert marker.read_text() == "4"


def test_workflow_memoizes_over_storage_uri(ray_start_2cpu, tmp_path):
    """Workflow storage is the pluggable storage plane: a mem:// root
    memoizes steps exactly like the filesystem default (README
    "Checkpointing & storage")."""
    from ray_tpu import workflow
    from ray_tpu.storage.mem import MemBackend

    MemBackend.clear_all()
    workflow.init("mem://wfstore")
    try:
        marker = tmp_path / "exec_count"
        marker.write_text("0")

        @ray_tpu.remote
        def bump(x, marker_path):
            p = __import__("pathlib").Path(marker_path)
            p.write_text(str(int(p.read_text()) + 1))
            return x + 1

        dag = bump.bind(41, str(marker))
        assert workflow.run(dag, workflow_id="wfm") == 42
        assert workflow.run(dag, workflow_id="wfm") == 42
        assert marker.read_text() == "1"  # memoized in mem://
        assert "wfm" in workflow.list_all()
        assert workflow.get_status("wfm")["status"] == "SUCCESSFUL"
    finally:
        workflow.init(str(tmp_path / "wf_default"))  # restore module state
        MemBackend.clear_all()


def test_channel_roundtrip_and_latency(ray_start_2cpu):
    from ray_tpu.experimental.channel import Channel

    ch = Channel(f"t{os.getpid()}", size=1 << 16)
    try:
        @ray_tpu.remote
        def echo_loop(name, n):
            from ray_tpu.experimental.channel import Channel as C

            rx = C(name, 1 << 16, _create=False)
            tx = C(name + "r", 1 << 16, _create=False)
            for _ in range(n):
                tx.write(rx.read(timeout=30))
            return True

        back = Channel(f"t{os.getpid()}r", size=1 << 16)
        ref = echo_loop.remote(f"t{os.getpid()}", 200)
        t0 = time.perf_counter()
        for i in range(200):
            ch.write(i)
            assert back.read(timeout=30) == i
        dt = (time.perf_counter() - t0) / 200
        assert ray_tpu.get(ref, timeout=60)
        # Cross-process ping-pong through shm must beat a typical RPC RTT.
        assert dt < 0.01, f"channel roundtrip {dt*1e6:.0f}us"
    finally:
        ch.close(unlink=True)
        back.close(unlink=True)


def test_compiled_dag_pipeline(ray_start_4cpu):
    from ray_tpu.dag import InputNode, compile

    @ray_tpu.remote
    def scale(x):
        return x * 10

    @ray_tpu.remote
    def shift(x):
        return x + 1

    with InputNode() as inp:
        dag = shift.bind(scale.bind(inp))
    cdag = compile(dag)
    try:
        assert cdag.execute(4).get(timeout=60) == 41
        # steady-state: repeated executes reuse the same channels/actors,
        # and multiple invocations stay in flight (pipelined DagRefs).
        refs = [cdag.execute(i) for i in range(20)]
        assert [r.get(timeout=60) for r in refs] == [
            i * 10 + 1 for i in range(20)]
    finally:
        cdag.teardown()


def test_llm_batch_inference_and_serve(ray_start_4cpu):
    from ray_tpu import data as rd
    from ray_tpu import serve
    from ray_tpu.llm import LLMConfig, batch_inference, build_llm_deployment

    cfg = LLMConfig(vocab_size=64, d_model=32, n_layers=1, n_heads=4,
                    max_seq=64, max_new_tokens=4)
    rng = np.random.RandomState(0)
    rows = [{"tokens": rng.randint(0, 64, 8).tolist()} for _ in range(6)]
    ds = batch_inference(rd.from_items(rows), cfg, concurrency=1)
    out = ds.take_all()
    assert len(out) == 6
    assert len(out[0]["generated"]) == 12  # 8 prompt + 4 new

    try:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        serve.run(build_llm_deployment(cfg), port=port)
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/",
            data=json.dumps({"tokens": rows[0]["tokens"],
                             "max_new_tokens": 3}).encode())
        rep = json.loads(urllib.request.urlopen(req, timeout=60).read())
        assert len(rep["generated"][0]) == 11
    finally:
        serve.shutdown()


def test_kv_cache_decode_matches_naive():
    """KV-cached greedy decode must produce EXACTLY the tokens the naive
    re-forward-the-context decode produces (the cache is an optimization,
    not a semantics change)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.llm import LLMConfig, LLMEngine

    cfg = LLMConfig(vocab_size=128, d_model=64, n_layers=2, n_heads=4,
                    max_seq=64, max_new_tokens=12, seed=3)
    eng = LLMEngine(cfg)

    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size, size=(2, 9), dtype=np.int64)

    out = eng.generate(prompts)
    assert out.shape == (2, 9 + 12)
    assert np.array_equal(out[:, :9], prompts)

    # Naive reference: re-forward the growing context each step.
    toks = jnp.asarray(prompts, jnp.int32)
    for _ in range(12):
        logits = eng.model.apply(eng.params, toks)
        nxt = jnp.argmax(logits[:, -1, :], axis=-1)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    assert np.array_equal(out, np.asarray(toks)), (out, np.asarray(toks))


def test_compiled_dag_fan_in_fan_out(ray_start_4cpu):
    """2-branch join DAG with a shared (fanned-out) upstream and multiple
    outputs (reference compiled_dag_node MultiOutputNode + fan-in)."""
    from ray_tpu.dag import InputNode, MultiOutputNode, compile

    @ray_tpu.remote
    def double(x):
        return x * 2

    @ray_tpu.remote
    def inc(x):
        return x + 1

    @ray_tpu.remote
    def join(a, b):
        return a + b  # fan-in: two upstream channels

    with InputNode() as inp:
        d = double.bind(inp)       # consumed by BOTH join and out2: fan-out
        i = inc.bind(inp)
        dag = MultiOutputNode([join.bind(d, i), inc.bind(d)])
    cdag = compile(dag)
    try:
        for x in (1, 5, 10):
            j, k = cdag.execute(x).get(timeout=60)
            assert j == 2 * x + (x + 1), (x, j)
            assert k == 2 * x + 1, (x, k)
    finally:
        cdag.teardown()


def test_compiled_dag_actor_methods(ray_start_4cpu):
    """Bound EXISTING-actor methods as DAG stages: the actor keeps its
    state across executes and still serves normal calls (reference
    actor.method.bind + experimental_compile)."""
    from ray_tpu.dag import InputNode, compile

    @ray_tpu.remote
    class Stateful:
        def __init__(self):
            self.calls = 0

        def scale(self, x):
            self.calls += 1
            return x * 10

        def count(self):
            return self.calls

    @ray_tpu.remote
    def plus1(x):
        return x + 1

    actor = Stateful.remote()
    with InputNode() as inp:
        dag = plus1.bind(actor.scale.bind(inp))
    cdag = compile(dag)
    try:
        assert cdag.execute(1).get(timeout=60) == 11
        assert cdag.execute(2).get(timeout=60) == 21
        assert cdag.execute(3).get(timeout=60) == 31
        # The actor's own state advanced AND it still answers normal calls
        # concurrently with the compiled loop.
        assert ray_tpu.get(actor.count.remote(), timeout=30) == 3
    finally:
        cdag.teardown()
    # actor survives teardown (it's user-owned, not a stage actor)
    assert ray_tpu.get(actor.count.remote(), timeout=30) == 3


def test_compiled_dag_stage_error_propagates(ray_start_2cpu):
    from ray_tpu.dag import InputNode, compile

    @ray_tpu.remote
    def boom(x):
        raise ValueError("kaput")

    @ray_tpu.remote
    def after(x):
        return x

    with InputNode() as inp:
        dag = after.bind(boom.bind(inp))
    cdag = compile(dag)
    try:
        from ray_tpu.exceptions import DagStageError

        with pytest.raises(DagStageError, match="kaput"):
            cdag.execute(1).get(timeout=60)
        # pipeline stays usable for the next execute
        with pytest.raises(DagStageError, match="kaput"):
            cdag.execute(2).get(timeout=60)
    finally:
        cdag.teardown()


def test_workflow_code_change_invalidates_memoization(ray_start_2cpu, tmp_path):
    """Editing a step's BODY changes its content key: the old memoized
    result must NOT replay for the same workflow_id (reference
    content-addresses steps via checkpointed DAG state)."""
    from ray_tpu import workflow

    workflow.init(str(tmp_path / "wf"))

    @ray_tpu.remote
    def step(x):
        return x + 1

    out = workflow.run(step.bind(10), workflow_id="wf-code")
    assert out == 11

    @ray_tpu.remote
    def step(x):  # noqa: F811 — same NAME, different body
        return x + 100

    out2 = workflow.run(step.bind(10), workflow_id="wf-code")
    assert out2 == 110, "stale memoized result replayed after code change"
