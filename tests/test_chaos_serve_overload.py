"""Chaos: serve overload + admission control under replica churn.

Pins the "shed, not stall" contract (README "Overload & admission
control"): under sustained overload every request resolves — success or
typed BackPressureError within the queue deadline — and a replica
SIGKILLed at full load never strands a client. reference spiritual kin:
python/ray/serve/tests/test_max_queued_requests.py,
test_backpressure.py, test_replica_failures.py.
"""

import json
import os
import signal
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _http(url, data=None, timeout=30):
    req = urllib.request.Request(url, data=data)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.read()


@pytest.fixture
def serve_shutdown():
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def _get_json(url, timeout=30):
    """GET -> (status, parsed-json-or-None, elapsed_s); never raises."""
    t0 = time.monotonic()
    try:
        body = _http(url, timeout=timeout)
        return 200, json.loads(body), time.monotonic() - t0
    except urllib.error.HTTPError as e:
        try:
            payload = json.loads(e.read())
        except Exception:
            payload = None
        return e.code, payload, time.monotonic() - t0
    except Exception:
        return -1, None, time.monotonic() - t0


def test_replica_sigkill_at_full_load_no_hangs(serve_shutdown):
    """SIGKILL one of two replicas while both are saturated and the queue
    is part-full: every client resolves — completed via the survivor
    (router re-admission under the retry budget) or shed typed within the
    deadline. Zero hangs, zero bare 500s."""
    ray_tpu.init(num_cpus=4)

    @serve.deployment(num_replicas=2, max_ongoing_requests=2,
                      max_queued_requests=8, queue_deadline_s=10.0,
                      ray_actor_options={"num_cpus": 0.5})
    class Work:
        def __call__(self, request=None):
            time.sleep(0.4)
            return {"pid": os.getpid()}

    port = _free_port()
    serve.run(Work.bind(), port=port)
    base = f"http://127.0.0.1:{port}"
    # Learn both replica pids before the storm.
    pids = set()
    deadline = time.time() + 30
    while len(pids) < 2 and time.time() < deadline:
        status, payload, _ = _get_json(f"{base}/", timeout=30)
        if status == 200:
            pids.add(payload["pid"])
    assert len(pids) == 2, f"saw replica pids {pids}"

    results = []
    lock = threading.Lock()

    def client():
        out = _get_json(f"{base}/", timeout=40)
        with lock:
            results.append(out)

    threads = [threading.Thread(target=client, daemon=True)
               for _ in range(16)]
    for t in threads:
        t.start()
    time.sleep(0.25)  # both replicas saturated, queue part-full
    victim = sorted(pids)[0]
    os.kill(victim, signal.SIGKILL)
    for t in threads:
        t.join(timeout=60)
    hung = [t for t in threads if t.is_alive()]
    assert not hung, f"{len(hung)} clients hung after replica SIGKILL"
    assert len(results) == 16
    ok = [r for r in results if r[0] == 200]
    shed = [r for r in results if r[0] in (429, 503)]
    other = [r for r in results if r[0] not in (200, 429, 503)]
    assert not other, f"untyped failures: {other}"
    # The survivor (plus the restarted replica) absorbs the backlog.
    assert len(ok) >= 8, f"only {len(ok)}/16 completed: {results}"
    for status, payload, elapsed in shed:
        assert payload and "error" in payload, (status, payload)
        # queue deadline 10s + retry/teardown slack
        assert elapsed < 15.0, f"shed took {elapsed:.1f}s"
    # The backlog drained through the survivor and/or the controller's
    # replacement replica (a fresh pid) — not the victim.
    assert any(r[1]["pid"] != victim for r in ok)


def test_sustained_overload_sheds_typed_and_streams_identical(
        serve_shutdown):
    """~10x overload on a capped LLM deployment: admitted SSE streams are
    byte-identical greedy decodes, excess is shed typed within the queue
    deadline, and nothing hangs."""
    from ray_tpu.llm import LLMConfig
    from ray_tpu.llm.openai import build_openai_app

    ray_tpu.init(num_cpus=4)
    cfg = LLMConfig(vocab_size=384, d_model=64, n_layers=2, n_heads=4,
                    max_seq=128)
    app = build_openai_app(cfg, model_id="overload-llm", max_batch=4,
                           decode_chunk=4, default_max_tokens=8,
                           max_ongoing_requests=2, max_queued_requests=1,
                           queue_deadline_s=2.0)
    port = _free_port()
    serve.run(app, route_prefix="/", port=port)
    base = f"http://127.0.0.1:{port}"
    # Warm the engine (first request JIT-compiles) outside the storm.
    body = json.dumps({"prompt": "hi", "max_tokens": 2,
                       "temperature": 0.0}).encode()
    _http(f"{base}/v1/completions", data=body, timeout=180)

    results = []
    lock = threading.Lock()

    def sse_client():
        t0 = time.monotonic()
        body = json.dumps({"prompt": "hi", "max_tokens": 8,
                           "temperature": 0.0, "stream": True}).encode()
        req = urllib.request.Request(
            f"{base}/v1/completions", data=body,
            headers={"Content-Type": "application/json"})
        try:
            toks = []
            with urllib.request.urlopen(req, timeout=60) as r:
                for line in r:
                    line = line.decode().strip()
                    if not line.startswith("data: "):
                        continue
                    payload = line[len("data: "):]
                    if payload == "[DONE]":
                        break
                    toks.extend(json.loads(payload).get("token_ids", []))
            out = ("ok", tuple(toks), time.monotonic() - t0)
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read())
            except Exception:
                payload = None
            out = ("shed", (e.code, payload), time.monotonic() - t0)
        except Exception as e:
            out = ("err", repr(e), time.monotonic() - t0)
        with lock:
            results.append(out)

    threads = [threading.Thread(target=sse_client, daemon=True)
               for _ in range(12)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not any(t.is_alive() for t in threads), "hung SSE clients"
    assert len(results) == 12
    ok = [r for r in results if r[0] == "ok"]
    shed = [r for r in results if r[0] == "shed"]
    errs = [r for r in results if r[0] == "err"]
    assert not errs, f"untyped failures under overload: {errs}"
    assert ok, "no requests admitted under overload"
    assert shed, "10x overload shed nothing (budgets not enforced?)"
    # Admitted streams: greedy decode, identical prompt -> identical bytes.
    streams = {r[1] for r in ok}
    assert len(streams) == 1, f"admitted streams diverged: {streams}"
    assert len(next(iter(streams))) == 8
    for _kind, (status, payload), elapsed in shed:
        assert status in (429, 503), status
        assert payload and payload["error"]["type"] == "BackPressureError"
        assert payload["error"]["reason"] in (
            "queue_full", "deadline", "replica_busy")
        # queue_deadline_s=2.0 plus scheduling slack: shed, never stalled
        assert elapsed < 8.0, f"shed resolved in {elapsed:.1f}s"


def test_token_bucket_sheds_burst_then_recovers(serve_shutdown,
                                                monkeypatch):
    """RT_SERVE_RPS front door: a burst beyond the bucket gets typed 429s
    with Retry-After, and the route recovers once tokens refill."""
    monkeypatch.setenv("RT_SERVE_RPS", "5")
    monkeypatch.setenv("RT_SERVE_BURST", "2")
    ray_tpu.init(num_cpus=4)

    @serve.deployment
    def echo(request):
        return {"ok": True}

    port = _free_port()
    serve.run(echo.bind(), port=port)
    base = f"http://127.0.0.1:{port}"
    time.sleep(1.0)  # let the bucket fill after the proxy boots
    statuses = []
    retry_after = None
    for _ in range(6):
        status, payload, _ = _get_json(f"{base}/", timeout=15)
        statuses.append(status)
        if status == 429:
            assert payload["error"]["reason"] == "rate_limit"
    assert 200 in statuses, statuses
    assert 429 in statuses, f"burst of 6 over bucket(2) not limited: " \
                            f"{statuses}"
    # Retry-After is surfaced on the shed response.
    try:
        for _ in range(4):
            _http(f"{base}/", timeout=15)
    except urllib.error.HTTPError as e:
        assert e.code == 429
        retry_after = int(e.headers["Retry-After"])
    assert retry_after is not None and retry_after >= 1
    # Refill: ~1s at 5 rps restores several tokens.
    time.sleep(1.2)
    status, payload, _ = _get_json(f"{base}/", timeout=15)
    assert status == 200 and payload == {"ok": True}
