"""Chaos coverage for the cluster event plane (README "Cluster events"):
kill a worker mid-task and the full causal chain appears ordered and
entity-indexed within the detection deadline; stall kills carry the
stalled task's trace_id; the ring stays bounded under churn and
persistence heals after a severed sim:// backend."""

import os
import signal
import time

import ray_tpu
from ray_tpu._private import events as events_mod
from ray_tpu.util import state


def _wait_for(pred, timeout=25.0, interval=0.2, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = pred()
        if out:
            return out
        time.sleep(interval)
    raise TimeoutError(f"timed out waiting for {what}")


def test_actor_kill_causal_event_chain(ray_start_2cpu):
    """SIGKILL an actor's worker mid-life: the replacement comes up and
    `list_events(entity=actor_id)` shows the ordered, entity-linked chain
    worker_exit{cause=crash} -> actor_restart -> actor_ready."""
    @ray_tpu.remote(max_restarts=1, max_task_retries=2)
    class Phoenix:
        def pid(self):
            return os.getpid()

    p = Phoenix.remote()
    pid = ray_tpu.get(p.pid.remote(), timeout=60)
    t0 = time.monotonic()
    os.kill(pid, signal.SIGKILL)
    # The replacement serves again — and the chain is queryable.
    pid2 = ray_tpu.get(p.pid.remote(), timeout=60)
    assert pid2 != pid

    def _chain():
        rows = state.list_events(entity=p._actor_id)
        kinds = [e["kind"] for e in rows]
        if "worker_exit" in kinds and "actor_restart" in kinds \
                and kinds.count("actor_ready") >= 2:
            return rows
        return None

    rows = _wait_for(_chain, what="causal event chain")
    detect_s = time.monotonic() - t0
    assert detect_s < 20, f"chain took {detect_s:.1f}s to appear"
    by_kind = {}
    for e in rows:
        by_kind.setdefault(e["kind"], []).append(e)
    exit_ev = by_kind["worker_exit"][0]
    restart_ev = by_kind["actor_restart"][0]
    ready_ev = by_kind["actor_ready"][-1]
    # Ordered by seq (the worker_died push carries the agent's exit event,
    # so arrival-order seqs preserve causality).
    assert exit_ev["seq"] < restart_ev["seq"] < ready_ev["seq"], rows
    # Normalized cause + entity linkage on every link of the chain.
    assert exit_ev["attrs"]["cause"] == events_mod.CAUSE_CRASH
    assert any(str(x).startswith(p._actor_id[:12])
               for x in exit_ev["entity"])
    assert restart_ev["sev"] == "warning"


def test_leased_worker_kill_emits_lease_failover(ray_start_2cpu):
    """Kill a LEASED worker mid-plain-task: the lease invalidates (specs
    fail over, task still completes via retry) and the event chain shows
    worker_exit -> lease_failover with the shared cause enum."""
    @ray_tpu.remote
    def slow(i):
        time.sleep(0.4)
        return i

    refs = [slow.remote(i) for i in range(8)]

    def _leased_pid():
        for slot in ray_tpu._head.agent.workers.values():
            if slot.state == "leased" and slot.proc.poll() is None:
                return slot.proc.pid
        return None

    pid = _wait_for(_leased_pid, what="a leased worker")
    os.kill(pid, signal.SIGKILL)
    # Retries absorb the kill: every task still completes.
    assert sorted(ray_tpu.get(refs, timeout=120)) == list(range(8))

    def _failover():
        exits = [e for e in state.list_events(kind="worker_exit")
                 if (e.get("attrs") or {}).get("pid") == pid]
        fails = state.list_events(kind="lease_failover")
        return (exits, fails) if exits and fails else None

    exits, fails = _wait_for(_failover, what="worker_exit + lease_failover")
    assert exits[0]["attrs"]["cause"] == events_mod.CAUSE_CRASH
    # Whichever side observed the failover first (the owner's severed
    # direct conn, or the controller's worker_died), the event names the
    # dead worker so the chain is entity-linked.
    wid = exits[0]["entity"][0]
    assert any(any(str(x).startswith(str(wid)[:12]) for x in e["entity"])
               for e in fails), (exits, fails)


def test_stall_kill_event_carries_trace_id(shutdown_only, tmp_path):
    """Acceptance: stall-kill events carry the trace_id of the stalled
    task, chaining `ray-tpu events` -> `ray-tpu timeline --trace`."""
    ray_tpu.init(num_cpus=2, _system_config={
        "tracing": True,
        "stall_warn_s": 0.6,
        "stall_kill_s": 1.5,
        "stall_beacon_interval_s": 0.2,
    })
    marker = str(tmp_path / "attempt")

    @ray_tpu.remote
    def wedge(path):
        import os as _os
        import time as _t

        n = int(open(path).read()) if _os.path.exists(path) else 0
        with open(path, "w") as f:
            f.write(str(n + 1))
        if n == 0:
            _t.sleep(120)  # silent stall on the first attempt
        return n + 1

    assert ray_tpu.get(wedge.remote(marker), timeout=60) == 2

    def _kill_event():
        rows = [e for e in state.list_events(kind="stall")
                if (e.get("attrs") or {}).get("stage") == "kill"]
        return rows or None

    rows = _wait_for(_kill_event, what="stall kill event")
    ev = rows[0]
    assert ev["sev"] == "error"
    assert ev.get("trace_id"), "stall-kill event lost its trace linkage"
    # The trace is resolvable — the events -> timeline chain works.
    tr = state.get_trace(ev["trace_id"])
    assert tr.get("found"), tr


def test_event_ring_churn_bounded_and_persistence_heals(
        shutdown_only, tmp_path, monkeypatch):
    """10k-event churn: bounded controller memory, keep-last-K segment
    rotation, and a severed sim:// backend sheds (counted) then persists
    again once healed."""
    from ray_tpu import storage

    ev_dir = "sim://" + str(tmp_path / "ev")
    monkeypatch.setenv("RT_EVENTS_DIR", ev_dir)
    monkeypatch.setenv("RT_EVENTS_BUFFER", "256")
    monkeypatch.setenv("RT_EVENTS_SEGMENT_EVENTS", "64")
    monkeypatch.setenv("RT_EVENTS_KEEP_SEGMENTS", "3")
    events_mod.refresh()
    storage.sim.faults().clear()
    try:
        ray_tpu.init(num_cpus=1)
        head = ray_tpu._head
        ctrl = head.controller

        async def _pump(n, tag):
            ctrl._ingest_events([
                events_mod.build_event("job_start", f"{tag} {i}",
                                       entity=(f"{tag}{i % 97}",))
            for i in range(n)])

        for _ in range(10):
            head.io.run(_pump(1000, "churn"))
        # Bounded memory: the arrival ring holds exactly the cap; the
        # persistence backlog is capped too; the entity index is capped.
        assert len(ctrl.events) == 256
        assert len(ctrl._evseg_buf) <= 256
        assert len(ctrl._event_index) <= ctrl._EVENT_INDEX_ENTITIES
        assert ctrl._event_seq >= 10_000
        # Oldest rotated out: the ring starts well past seq 0, and the
        # list API serves only the retained window (truncated flagged).
        assert ctrl.events[0]["seq"] >= 10_000 - 256
        rep = state.list_events(limit=100)
        assert rep.truncated and len(rep) == 100

        def _segments():
            try:
                return [n for n in storage.listdir(ev_dir)
                        if n.startswith("seg-")]
            except Exception:
                return []

        _wait_for(lambda: _segments() or None, what="first segments")
        assert len(_segments()) <= 3  # keep-last-K

        # --- sever the backend mid-stream ------------------------------
        storage.sim.faults().sever()
        head.io.run(_pump(50, "severed"))
        time.sleep(2.5)  # sweeps fail; buffer retains/sheds, never crashes
        assert ctrl._event_seq >= 10_050
        # --- heal: persistence picks up where it left off --------------
        storage.sim.faults().restore()
        head.io.run(_pump(10, "healed"))
        target = ctrl._event_seq - 1

        def _persisted_past_target():
            try:
                names = storage.listdir(ev_dir)
            except Exception:
                return False
            hi = -1
            for n in names:
                if n.startswith("seg-"):
                    hi = max(hi, int(n[len("seg-"):-len(".jsonl")]))
            if hi >= target:
                return True
            try:
                import json as _json

                lines = storage.get_bytes(
                    storage.join(ev_dir, "current.jsonl")).splitlines()
                return bool(lines) and _json.loads(
                    lines[-1])["seq"] >= target
            except Exception:
                return False

        _wait_for(_persisted_past_target, what="post-heal persistence")
    finally:
        storage.sim.faults().clear()
        events_mod.refresh()
