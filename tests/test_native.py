"""Native (C++) runtime components: futex ring channel + parallel memcpy.

Parity rationale: the reference's channel/object hot paths are C++
(experimental_mutable_object_manager.h, plasma); ray_tpu/_native/ring.cc is
the TPU-host equivalent, JIT-built with g++ and bound via ctypes with a
pure-Python fallback.
"""

import multiprocessing as mp
import time

import numpy as np
import pytest

from ray_tpu._native import get_lib, parallel_memcpy


def test_native_lib_builds():
    assert get_lib() is not None, "g++ toolchain present; native must build"


def test_parallel_memcpy_correctness():
    a = np.random.default_rng(0).integers(0, 256, size=9_000_000, dtype=np.uint8)
    dst = bytearray(len(a))
    assert parallel_memcpy(memoryview(dst), a)
    assert bytes(dst) == a.tobytes()


REPO = __import__("os").path.dirname(__import__("os").path.dirname(
    __import__("os").path.abspath(__file__)))


def _child_echo(name, n_msgs):
    import sys

    sys.path.insert(0, REPO)
    from ray_tpu.experimental.channel import Channel

    a = Channel(name + "_req", _create=False)
    b = Channel(name + "_rep", _create=False)
    for _ in range(n_msgs):
        b.write(a.read(timeout=30))


def test_channel_native_roundtrip_cross_process(tmp_path):
    from ray_tpu.experimental.channel import Channel

    name = f"tnat_{time.time_ns()}"
    req = Channel(name + "_req")
    rep = Channel(name + "_rep")
    n = 300
    p = mp.get_context("spawn").Process(target=_child_echo, args=(name, n),
                                        daemon=True)
    p.start()
    try:
        t0 = time.perf_counter()
        for i in range(n):
            req.write({"i": i, "data": b"x" * 256})
            out = rep.read(timeout=30)
            assert out["i"] == i
        dt = time.perf_counter() - t0
        # Sanity: futex path must stay well under the Python-poll baseline.
        assert dt / n < 0.05, f"{dt/n*1e6:.0f}us per round trip"
    finally:
        p.join(timeout=30)
        req.close(unlink=True)
        rep.close(unlink=True)


def test_channel_python_fallback_interops(monkeypatch, tmp_path):
    """A reader forced onto the pure-Python path still talks to a native
    writer (shared header layout; bounded native waits)."""
    import ray_tpu._native as native
    from ray_tpu.experimental import channel as chmod

    name = f"tfall_{time.time_ns()}"
    w = chmod.Channel(name)
    monkeypatch.setattr(chmod, "_native", lambda: None)
    r = chmod.Channel(name, _create=False)
    assert r._lib is None and w._lib is not None
    w.write([1, 2, 3])
    assert r.read(timeout=10) == [1, 2, 3]
    w.write("second")
    assert r.read(timeout=10) == "second"
    w.close()
    r.close(unlink=True)


def test_ring_copying_read_roundtrip():
    """rt_ring_read (copy-out variant of the wait/ack pair) stays correct."""
    import ctypes
    import mmap

    lib = get_lib()
    size = 1 << 12
    mm = mmap.mmap(-1, 64 + size)
    view = (ctypes.c_char * len(mm)).from_buffer(mm)
    base = ctypes.addressof(view)
    msg = b"copying-read-path" * 3
    assert lib.rt_ring_write(base, size, msg, len(msg), int(1e9)) == 0
    out = ctypes.create_string_buffer(size)
    n = lib.rt_ring_read(base, size, out, 0, int(1e9))
    assert n == len(msg) and out.raw[:n] == msg
    assert lib.rt_ring_read(base, size, out, 1, int(20e6)) == -1  # timeout
    del view
