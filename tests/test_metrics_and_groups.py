"""Application metrics + actor concurrency groups.

Parity targets: reference python/ray/util/metrics.py (Counter/Gauge/
Histogram export) and python/ray/tests/test_concurrency_group.py
(per-group execution limits, @ray.method(concurrency_group=...)).
"""

import time

import ray_tpu
from ray_tpu.util import state
from ray_tpu.util.metrics import Counter, Gauge, Histogram


def _wait_for(pred, timeout=30.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.2)
    raise TimeoutError(f"timed out: {what}")


def test_metrics_roundtrip(ray_start_2cpu):
    c = Counter("requests_total", description="reqs", tag_keys=("route",))
    c.inc(tags={"route": "/a"})
    c.inc(2, tags={"route": "/a"})
    g = Gauge("queue_depth", tag_keys=())
    g.set(7)
    h = Histogram("latency_ms", boundaries=[1, 10, 100], tag_keys=())
    for v in (0.5, 5, 50, 500):
        h.observe(v)

    def _find(name):
        return [m for m in state.metrics() if m["name"] == name]

    _wait_for(lambda: _find("latency_ms"), what="metrics flushed")
    (cnt,) = _find("requests_total")
    assert cnt["value"] == 3.0 and cnt["tags"] == {"route": "/a"}
    (gau,) = _find("queue_depth")
    assert gau["value"] == 7.0
    (hist,) = _find("latency_ms")
    assert hist["count"] == 4 and hist["buckets"] == [1, 1, 1, 1]


def test_metrics_from_remote_worker(ray_start_2cpu):
    @ray_tpu.remote
    def work():
        from ray_tpu.util.metrics import Counter as C

        c = C("worker_side_total", tag_keys=())
        c.inc(5)
        from ray_tpu.util.metrics import _flush_now

        _flush_now()  # don't wait out the 1s flush tick in a short task
        return True

    assert ray_tpu.get(work.remote(), timeout=60)
    _wait_for(lambda: any(m["name"] == "worker_side_total" and m["value"] == 5.0
                          for m in state.metrics()),
              what="worker metric aggregated")


def test_metrics_tail_flushed_on_shutdown(shutdown_only):
    """Counters minted right before ray_tpu.shutdown() must reach the
    controller: Worker.disconnect force-flushes the final pending batch and
    fences it with an acked ping — a short-lived driver no longer loses its
    last second of metrics (and trailing tracing spans) to the flusher's
    shutdown guard."""
    ray_tpu.init(num_cpus=1)
    c = Counter("rt_test_tail_total", description="tail", tag_keys=())
    c.inc(5)
    ctrl = ray_tpu._head.controller  # survives shutdown as a Python object
    ray_tpu.shutdown()
    vals = [m["value"] for m in ctrl.metrics.values()
            if m["name"] == "rt_test_tail_total"]
    assert vals == [5.0], (
        f"final metrics batch dropped on shutdown: {vals}")


def test_histogram_boundaries_registered_once(monkeypatch):
    """Bucket boundaries ride ONE histogram_decl record per (name,
    boundaries) per session; observe records carry values only — at
    hot-path observation rates (tracing's RPC-frame / decode-step
    histograms) shipping the boundary list per record bloated every flush
    batch."""
    from ray_tpu.util import metrics as m

    captured = []
    monkeypatch.setattr(m, "_record", captured.append)
    m._hist_declared.discard(("rt_test_decl_ms", (1.0, 10.0)))
    h = Histogram("rt_test_decl_ms", boundaries=[1, 10], tag_keys=())
    for v in (0.5, 5.0, 50.0):
        h.observe(v)
    decls = [r for r in captured if r["kind"] == "histogram_decl"]
    obs = [r for r in captured if r["kind"] == "histogram"]
    assert len(decls) == 1 and decls[0]["boundaries"] == [1.0, 10.0]
    assert len(obs) == 3
    assert all("boundaries" not in r for r in obs)
    # A second instance with the SAME (name, boundaries) re-declares
    # nothing; different boundaries do get their own decl.
    Histogram("rt_test_decl_ms", boundaries=[1, 10], tag_keys=()).observe(2)
    assert len([r for r in captured if r["kind"] == "histogram_decl"]) == 1
    Histogram("rt_test_decl_ms", boundaries=[1, 10, 100],
              tag_keys=()).observe(2)
    assert len([r for r in captured if r["kind"] == "histogram_decl"]) == 2


def test_histogram_decl_aggregates_controller_side(ray_start_2cpu):
    """End to end: decl-once histograms still bucket correctly at the
    controller (the roundtrip test above covers the single-record shape;
    this pins the registry path)."""
    from ray_tpu.util import metrics as m

    m._hist_declared.discard(("rt_test_e2e_ms", (1.0, 10.0, 100.0)))
    h = Histogram("rt_test_e2e_ms", boundaries=[1, 10, 100], tag_keys=())
    for v in (0.5, 5, 50, 500):
        h.observe(v)

    def _find():
        return [x for x in state.metrics() if x["name"] == "rt_test_e2e_ms"]

    _wait_for(lambda: _find() and _find()[0]["count"] == 4,
              what="decl-once histogram aggregated")
    (hist,) = _find()
    assert hist["buckets"] == [1, 1, 1, 1]
    assert hist["boundaries"] == [1.0, 10.0, 100.0]


def test_concurrency_groups_parallelism(ray_start_2cpu):
    """Two calls in a group with limit 2 overlap; the default group (limit 1)
    stays serial and is NOT blocked by a saturated other group."""

    @ray_tpu.remote(concurrency_groups={"io": 2})
    class G:
        def __init__(self):
            self.t0 = time.monotonic()

        @ray_tpu.method(concurrency_group="io")
        def io_sleep(self):
            time.sleep(1.0)
            return time.monotonic() - self.t0

        def quick(self):
            return "ok"

    g = G.remote()
    t0 = time.monotonic()
    r1 = g.io_sleep.remote()
    r2 = g.io_sleep.remote()
    # Saturate "io", then call the default group: it must not queue behind.
    time.sleep(0.1)
    assert ray_tpu.get(g.quick.remote(), timeout=30) == "ok"
    assert time.monotonic() - t0 < 0.9, "default group blocked behind io group"
    ray_tpu.get([r1, r2], timeout=60)
    # Both io calls ran concurrently: wall time ~1s, not ~2s.
    assert time.monotonic() - t0 < 1.9


def test_concurrency_group_async_actor(ray_start_2cpu):
    @ray_tpu.remote(concurrency_groups={"slow": 2})
    class A:
        @ray_tpu.method(concurrency_group="slow")
        async def nap(self):
            import asyncio

            await asyncio.sleep(0.8)
            return 1

        async def ping(self):
            return "pong"

    a = A.remote()
    t0 = time.monotonic()
    refs = [a.nap.remote(), a.nap.remote()]
    assert ray_tpu.get(a.ping.remote(), timeout=30) == "pong"
    assert sum(ray_tpu.get(refs, timeout=60)) == 2
    assert time.monotonic() - t0 < 1.7  # 2 naps overlapped in the group


def test_pubsub_actor_channel_and_user_channel(ray_start_2cpu):
    """Subscribers see controller-published actor lifecycle events and
    application events (reference GCS pubsub, pubsub/publisher.h:300)."""
    from ray_tpu.util import pubsub

    sub = pubsub.subscribe(["actor", "custom"])
    try:
        @ray_tpu.remote
        class P:
            def hi(self):
                return "hi"

        p = P.remote()
        assert ray_tpu.get(p.hi.remote(), timeout=60) == "hi"
        ev = sub.poll(timeout=30)
        assert ev is not None and ev[0] == "actor"
        assert ev[1]["state"] in ("ALIVE", "RESTARTING", "DEAD")

        pubsub.publish("custom", {"k": 41})
        for _ in range(50):
            ev = sub.poll(timeout=10)
            assert ev is not None, "no custom event arrived"
            if ev[0] == "custom":
                assert ev[1] == {"k": 41}
                break
        else:
            raise AssertionError("custom channel event not seen")
    finally:
        sub.close()


def test_prometheus_endpoint(ray_start_2cpu):
    import urllib.request

    from ray_tpu.dashboard import start_dashboard
    from ray_tpu.util.metrics import Counter, _flush_now

    Counter("prom_check_total", tag_keys=()).inc(3)
    _flush_now()
    time.sleep(0.3)
    d = start_dashboard(port=0)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{d.port}/metrics", timeout=10) as r:
            text = r.read().decode()
        assert "# TYPE prom_check_total counter" in text, text
        assert "prom_check_total 3.0" in text, text
    finally:
        d.stop()


def test_mixed_sync_async_group_shares_budget(ray_start_2cpu):
    """A group with limit 1 holding one sync and one async method must never
    run both at once (single shared budget across executor paths)."""

    @ray_tpu.remote(concurrency_groups={"x": 1})
    class M:
        def __init__(self):
            self.active = 0
            self.max_active = 0

        def _enter(self):
            self.active += 1
            self.max_active = max(self.max_active, self.active)

        @ray_tpu.method(concurrency_group="x")
        def sync_op(self):
            self._enter()
            time.sleep(0.4)
            self.active -= 1

        @ray_tpu.method(concurrency_group="x")
        async def async_op(self):
            import asyncio

            self._enter()
            await asyncio.sleep(0.4)
            self.active -= 1

        def peak(self):
            return self.max_active

    m = M.remote()
    refs = [m.sync_op.remote(), m.async_op.remote(), m.sync_op.remote()]
    ray_tpu.get(refs, timeout=60)
    assert ray_tpu.get(m.peak.remote(), timeout=30) == 1


def test_method_num_returns(ray_start_2cpu):
    @ray_tpu.remote
    class Two:
        @ray_tpu.method(num_returns=2)
        def pair(self):
            return 1, 2

    t = Two.remote()
    a, b = t.pair.remote()
    assert ray_tpu.get([a, b], timeout=30) == [1, 2]
