"""Deterministic chaos tests for owner-side direct dispatch.

The direct path keeps the controller off the per-task critical path, so its
failure story is owner-based: severing the owner->worker lease connection
mid-batch must fail the in-flight specs over to the classic controller path
with NO duplicate execution (worker-side skip of unstarted specs + the node
agent's task-id dedup of the one that was executing) and no hung refs; and
a lease reasserted against a node's PREVIOUS incarnation is dead on arrival
(fencing), never a resource charge against the fresh life.
"""

import json
import os
import subprocess
import sys
import tempfile
import time

import pytest

import ray_tpu
from ray_tpu._private import rpc
from ray_tpu._private.ids import NodeID
from ray_tpu._private.resources import ResourceSet


def _spawn_agent(controller_addr: str, session: str, num_cpus=2):
    env = dict(os.environ)
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    driver_paths = [p for p in sys.path if p and os.path.exists(p)]
    env["PYTHONPATH"] = os.pathsep.join([pkg_root] + driver_paths)
    node_id = NodeID.from_random().hex()
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.node_agent",
         "--controller", controller_addr,
         "--node-id", node_id,
         "--session", session,
         "--resources",
         json.dumps(ResourceSet({"CPU": float(num_cpus)}).raw())],
        env=env)
    return node_id, proc


def _wait(pred, timeout: float, what: str):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise TimeoutError(f"timed out waiting for {what}")


def _snapshot():
    return ray_tpu._private.worker.global_worker().state_snapshot()


@pytest.fixture
def chaos_cleanup():
    procs = []
    yield procs
    try:
        ray_tpu.shutdown()
    except Exception:
        pass
    for proc in procs:
        try:
            proc.kill()
        except Exception:
            pass
    inj = rpc.fault_injector()
    if inj is not None:
        inj.clear()
    rpc.disable_fault_injection()


def test_sever_mid_batch_fails_over_to_controller_no_duplicates(chaos_cleanup):
    """Sever every owner->worker lease connection while a batch is in
    flight: all refs still resolve (failover via the controller path), each
    task executed EXACTLY once (the worker skips unstarted specs of the
    dead holder; the agent's dedup absorbs the re-dispatch of the one that
    was executing), and the dispatch-path counters show the reroute."""
    ray_tpu.init(num_cpus=0, _system_config={"fault_injection": True})
    head = ray_tpu._head
    addr = f"{head.controller_addr[0]}:{head.controller_addr[1]}"
    nid, proc = _spawn_agent(addr, head.session_id, num_cpus=2)
    chaos_cleanup.append(proc)
    _wait(lambda: (_snapshot()["nodes"].get(nid) or {}).get("alive"),
          60, "node to register")

    marker_dir = tempfile.mkdtemp(prefix="rt_chaos_dd_")
    log = os.path.join(marker_dir, "executions.log")

    @ray_tpu.remote(num_cpus=1, max_retries=0)
    def tracked(i, path):
        import os as _os
        import time as _t

        # O_APPEND single write: concurrent executions can't interleave.
        fd = _os.open(path, _os.O_WRONLY | _os.O_CREAT | _os.O_APPEND, 0o644)
        _os.write(fd, f"{i}\n".encode())
        _os.close(fd)
        _t.sleep(0.15)
        return i

    # Warm the leases/workers so the sever hits established pipelines.
    ray_tpu.get([tracked.remote(-1 - j, log) for j in range(2)], timeout=60)

    n = 12
    refs = [tracked.remote(i, log) for i in range(n)]

    def _started():
        try:
            with open(log) as f:
                return sum(1 for l in f if not l.startswith("-")) >= 2
        except OSError:
            return False

    _wait(_started, 30, "batch to start executing")
    inj = rpc.fault_injector()
    severed = inj.sever("lease")
    assert severed >= 1, "no lease connections to sever"

    # Every ref resolves despite the sever (no hung refs), max_retries=0
    # notwithstanding: a transport sever is a re-route, not a retry.
    values = ray_tpu.get(refs, timeout=120)
    assert values == list(range(n))

    # Exactly-once: each index appears exactly once in the execution log.
    with open(log) as f:
        runs = [int(l) for l in f if l.strip()]
    counts = {}
    for i in runs:
        if i >= 0:
            counts[i] = counts.get(i, 0) + 1
    assert counts == {i: 1 for i in range(n)}, counts

    # The failover went through the controller path (owner-side counter).
    from ray_tpu.util.metrics import task_dispatch_counts

    counts = task_dispatch_counts()
    assert counts["controller"] > 0, counts
    assert counts["direct"] >= n, counts

    # And the cluster still works on fresh leases afterwards.
    assert ray_tpu.get([tracked.remote(100 + j, log) for j in range(4)],
                       timeout=60) == [100, 101, 102, 103]


def test_lease_fencing_across_incarnation_bump(chaos_cleanup):
    """A lease reasserted against a node's previous incarnation is dead on
    arrival: rejected (counted + lease_invalid pushed to the owner), with
    ZERO resource consumption on the node's fresh life; the same reassert
    with the current incarnation is accepted and charged."""
    ray_tpu.init(num_cpus=1, _system_config={"fault_injection": True})
    ctrl = ray_tpu._head.controller
    addr = ray_tpu._head.controller_addr
    io = rpc.EventLoopThread(name="fence-io")
    nid = "fence" + NodeID.from_random().hex()[:8]
    try:
        async def _register():
            conn = await rpc.connect(*addr)
            rep = await conn.call(
                "register", kind="node", node_id=nid,
                address=("127.0.0.1", 1),
                resources=ResourceSet({"CPU": 2.0}).raw(), labels={})
            return conn, rep["incarnation"]

        _old_conn, old_inc = io.run(_register(), timeout=30)
        _new_conn, new_inc = io.run(_register(), timeout=30)
        assert new_inc == old_inc + 1

        invalidated = []

        async def _owner():
            conn = await rpc.connect(
                *addr,
                on_push=lambda c, m, a: invalidated.append((m, a)) or _noop())
            await conn.call("register", kind="client",
                            worker_id="fenceowner" + "0" * 23,
                            mode="driver", address=("127.0.0.1", 2))
            return conn

        def _noop():
            async def _n():
                return None
            return _n()

        owner_conn = io.run(_owner(), timeout=30)
        node = ctrl.nodes[nid]
        avail_before = dict(node.available.raw())
        rejected_before = ctrl.stale_incarnation_rejections

        stale = {
            "lease_id": "stalelease0000ff",
            "worker_id": "w" * 32,
            "node_id": nid,
            "address": ("127.0.0.1", 3),
            "incarnation": old_inc,
            "resources": ResourceSet({"CPU": 1.0}).raw(),
            "strategy": None,
        }
        io.run(owner_conn.push("reassert_leases", leases=[stale],
                               owner_id="fenceowner" + "0" * 23))
        _wait(lambda: ctrl.stale_incarnation_rejections > rejected_before,
              10, "stale lease reassert to be rejected")
        assert "stalelease0000ff" not in ctrl.leases
        assert node.available.raw() == avail_before, \
            "fenced lease charged resources against the fresh incarnation"
        _wait(lambda: any(m == "lease_invalid" for m, _a in invalidated),
              10, "owner to be told the fenced lease is invalid")

        # Current-incarnation reassert: accepted and charged.
        from ray_tpu._private.task_spec import SchedulingStrategy

        fresh = dict(stale, lease_id="freshlease0000ff",
                     incarnation=new_inc, strategy=SchedulingStrategy())
        io.run(owner_conn.push("reassert_leases", leases=[fresh],
                               owner_id="fenceowner" + "0" * 23))
        _wait(lambda: "freshlease0000ff" in ctrl.leases, 10,
              "current-incarnation lease reassert to be applied")
        assert node.available.raw() != avail_before
    finally:
        io.stop()
