"""Pipeline-parallel LLM decode on the compiled DAG plane (ISSUE 18).

Pins the tentpole contracts: stage slicing is an exact partition of the
single-process model (greedy decode is bit-identical between the 2-stage
PipelinedEngine and ContinuousEngine at the same seed), steady-state
activations ride device-object edges as placeholders with ZERO resolve
RPCs, the stage collective group is pre-negotiated at graph-build time
(no controller KV rendezvous), and the engine is a drop-in behind the
OpenAI serving surface. Satellite pins ride along: the flash-attention
tile clamp for the bench shape and the bench's fallback-flag (never
negative TFLOP/s) contract.
"""

import json
import time
import types
import urllib.request

import numpy as np
import pytest

import ray_tpu
from ray_tpu.llm import LLMConfig
from ray_tpu.llm.engine import (ContinuousEngine, SamplingParams,
                                stage_layer_split, stage_param_slice)

# Small enough for seconds-scale CPU tests; d_model=64 x microbatch=4 puts
# the decode activation (4*1*64 f32 = 1KiB) exactly at the device-edge
# placeholder threshold, so the zero-RPC path is exercised for real.
CFG_KW = dict(vocab_size=128, d_model=64, n_layers=2, n_heads=4,
              max_seq=64)


# ------------------------------------------------------- stage slicing unit
def test_stage_layer_split_balanced_remainder_early():
    assert stage_layer_split(4, 2) == [(0, 1), (2, 3)]
    # Remainder layers land on the EARLIEST stages (the last stage already
    # carries final_norm + the tied head + the sampler).
    assert stage_layer_split(7, 3) == [(0, 1, 2), (3, 4), (5, 6)]
    assert stage_layer_split(3, 3) == [(0,), (1,), (2,)]
    with pytest.raises(ValueError, match="n_stages"):
        stage_layer_split(2, 3)
    with pytest.raises(ValueError, match="n_stages"):
        stage_layer_split(2, 0)


def test_stage_param_slice_global_names():
    params = {"tok_emb": "E", "final_norm": "N",
              **{f"layer_{i}": f"L{i}" for i in range(4)}}
    first = stage_param_slice(params, (0, 1), first=True, last=False)
    last = stage_param_slice(params, (2, 3), first=False, last=True)
    # Layer keys keep their GLOBAL names: a shard is a strict subtree of
    # the full checkpoint, not a renumbered copy.
    assert first == {"tok_emb": "E", "layer_0": "L0", "layer_1": "L1"}
    assert last == {"tok_emb": "E", "final_norm": "N",
                    "layer_2": "L2", "layer_3": "L3"}
    mid = stage_param_slice(params, (1,), first=False, last=False)
    assert mid == {"layer_1": "L1"}
    # Shards partition the layers exactly — nothing dropped, nothing
    # duplicated across a 2-way split.
    split = stage_layer_split(4, 2)
    layer_keys = [k for s, layers in enumerate(split)
                  for k in stage_param_slice(params, layers, s == 0, s == 1)
                  if k.startswith("layer_")]
    assert sorted(layer_keys) == sorted(f"layer_{i}" for i in range(4))


# ------------------------------------------------------------ engine parity
def test_pipeline_greedy_parity_with_single_process(ray_start_4cpu):
    """Greedy decode through the 2-stage pipeline is BIT-IDENTICAL to the
    single-process engine at the same seed — pipelining is a partition of
    the same model, not an approximation of it."""
    from ray_tpu.llm.pipeline import PipelinedEngine

    single = ContinuousEngine(LLMConfig(**CFG_KW), max_batch=4,
                              decode_chunk=4)
    pipe = PipelinedEngine(LLMConfig(**CFG_KW), n_stages=2, max_batch=4,
                           microbatch=2)
    try:
        prompts = [[1, 2, 3], [9, 8], [17], [4, 5, 6, 7]]
        sp = SamplingParams(temperature=0.0, max_tokens=12)
        want = single.generate(prompts, sp)
        got = pipe.generate(prompts, sp)
        assert got == want
        # And again — stage KV caches must reset cleanly between rounds.
        assert pipe.generate(prompts, sp) == want
    finally:
        pipe.shutdown()
        single.shutdown()


def test_pipeline_sampled_decode_and_active_count(ray_start_4cpu):
    from ray_tpu.llm.pipeline import PipelinedEngine

    pipe = PipelinedEngine(LLMConfig(**CFG_KW), n_stages=2, max_batch=4,
                           microbatch=2)
    try:
        sp = SamplingParams(temperature=0.8, top_k=20, max_tokens=10,
                            seed=7)
        outs = pipe.generate([[1, 2], [3, 4], [5, 6]], sp)
        for toks in outs:
            assert len(toks) == 10
            assert all(0 <= t < CFG_KW["vocab_size"] for t in toks)
        assert pipe.num_active == 0
        with pytest.raises(ValueError, match="max_seq"):
            pipe.submit(list(range(60)), SamplingParams(max_tokens=60))
    finally:
        pipe.shutdown()


def test_pipeline_zero_rpc_steady_state(ray_start_4cpu):
    """The zero-RPC proof, from the stages' own resolve counters: over a
    post-warmup decode window, activation placeholders flow on every
    inter-stage edge (edge_pins > 0), every consumer resolve lands in the
    local device store (store_hits > 0), and NO resolve takes an
    export/fetch RPC."""
    from ray_tpu.llm.pipeline import PipelinedEngine

    pipe = PipelinedEngine(LLMConfig(**CFG_KW), n_stages=2, max_batch=8,
                           microbatch=4)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=16)
        pipe.generate([[1, 2, 3]] * 8, sp)  # warm: jits + channel loops
        pipe.reset_pipeline_stats()
        pipe.generate([[i + 1, i + 2] for i in range(8)], sp)
        stats = pipe.pipeline_stats()
        assert stats["edge_pins"] > 0, (
            f"no placeholders pinned on activation edges: {stats}")
        assert stats["store_hits"] > 0, stats
        assert stats["resolve_rpcs"] == 0, (
            f"steady-state decode took resolve RPCs: {stats}")
        # Per-stage occupancy counters feed the rt_llm_pp_* gauges and
        # `ray-tpu top`'s PP% column: both stages did real work.
        assert len(stats["stages"]) == 2
        for s in stats["stages"]:
            assert s["steps"] > 0 and s["busy_s"] > 0
    finally:
        pipe.shutdown()


def test_occupancy_snapshot_windowed_per_consumer():
    """occupancy_snapshot is windowed PER CONSUMER: the first call anchors
    (0.0), later calls report busy fraction of wall time since that
    consumer's previous call — telemetry and metrics drains don't steal
    each other's windows."""
    from ray_tpu.llm import pipeline as pp

    stage = "pp-test-occ"
    pp._occ_record(stage, 0.0)
    assert pp.occupancy_snapshot("occ-a")[stage] == 0.0  # anchor
    pp.occupancy_snapshot("occ-b")  # anchor a second consumer
    pp._occ_record(stage, 0.04)
    time.sleep(0.08)
    frac_a = pp.occupancy_snapshot("occ-a")[stage]
    assert 0.0 < frac_a <= 1.0
    # Consumer b's window covers the same busy time independently.
    frac_b = pp.occupancy_snapshot("occ-b")[stage]
    assert 0.0 < frac_b <= 1.0
    # a's window restarted at its last call: immediately re-reading
    # reports ~0 busy fraction, not the cumulative one.
    assert pp.occupancy_snapshot("occ-a")[stage] < frac_a


# --------------------------------------------- pre-negotiated stage group
def test_prenegotiated_group_skips_kv_rendezvous(ray_start_4cpu):
    """init_prenegotiated_group: the coordinator gathers addresses ONCE
    and pushes the full rank->addr map; joining publishes nothing to the
    controller KV (no `col/<group>/addr/<rank>` keys ever exist) and the
    group still allreduces correctly."""
    from ray_tpu._private.worker import global_worker

    @ray_tpu.remote
    class PreWorker:
        def addr(self):
            from ray_tpu._private.worker import global_worker as gw

            return tuple(gw().server_addr)

        def join(self, world, rank, addrs, group):
            from ray_tpu.util import collective as col

            col.init_prenegotiated_group(world, rank, addrs, group,
                                         connect=True)
            return True

        def allreduce(self, value, group):
            from ray_tpu.util import collective as col

            return col.allreduce(np.asarray(value, np.float32),
                                 group_name=group)

    ws = [PreWorker.remote() for _ in range(2)]
    addrs = {r: ray_tpu.get(w.addr.remote(), timeout=60)
             for r, w in enumerate(ws)}
    g = "pre-dag"
    assert ray_tpu.get([w.join.remote(2, r, addrs, g)
                        for r, w in enumerate(ws)], timeout=60) == [True] * 2
    out = ray_tpu.get([w.allreduce.remote([float(r), 1.0], g)
                       for r, w in enumerate(ws)], timeout=120)
    for o in out:
        np.testing.assert_allclose(o, [0.0 + 1.0, 2.0])
    # The rendezvous namespace never saw this group: membership was
    # compile-time wiring, not controller KV polling.
    keys = global_worker().kv("keys", ns="collective",
                              prefix=f"col/{g}/addr")["keys"]
    assert keys == [], f"pre-negotiated group leaked rendezvous keys: {keys}"


def test_prenegotiated_group_validates_address_map(ray_start_2cpu):
    from ray_tpu.util import collective as col

    with pytest.raises(ValueError, match="address map"):
        col.init_prenegotiated_group(2, 0, {0: ("h", 1)}, "pre-bad")
    with pytest.raises(ValueError, match="address map"):
        col.init_prenegotiated_group(2, 0, {0: ("h", 1), 2: ("h", 2)},
                                     "pre-bad2")


# ------------------------------------------------- OpenAI drop-in surface
def test_openai_serve_over_pipeline_engine(ray_start_4cpu):
    """build_openai_app(pipeline_stages=2) swaps the pipeline engine in
    behind the SAME streaming surface: completions work over HTTP and
    /v1/stats reports the stage count."""
    import socket

    from ray_tpu import serve
    from ray_tpu.llm.openai import build_openai_app

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    app = build_openai_app(LLMConfig(**CFG_KW), model_id="pp-llm",
                           max_batch=4, default_max_tokens=8,
                           pipeline_stages=2)
    serve.run(app, route_prefix="/", port=port)
    try:
        base = f"http://127.0.0.1:{port}"
        body = json.dumps({"prompt": "hi", "max_tokens": 5,
                           "temperature": 0.0}).encode()
        req = urllib.request.Request(
            f"{base}/v1/completions", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            out = json.loads(r.read())
        assert len(out["token_ids"]) == 5
        assert out["choices"][0]["finish_reason"] == "length"
        with urllib.request.urlopen(f"{base}/v1/stats", timeout=30) as r:
            stats = json.loads(r.read())
        assert stats["pipeline_stages"] == 2
    finally:
        serve.shutdown()


# ------------------------------------------------------- satellite pins
def test_flash_attention_bench_shape_tiles():
    """The bench shape (b4 s2048 h8 d128) derives valid TPU tiles — the
    (8, 128) sublane/lane clamp that un-broke the flash-attention lane.
    Explicit caller blocks are preferences, re-clamped the same way."""
    from ray_tpu.ops.flash_attention import derive_blocks

    assert derive_blocks(2048, 2048) == (512, 1024)
    # Minimum-tile shapes resolve to the minimum tile, not a violation.
    assert derive_blocks(8, 128) == (8, 128)
    # Caller preferences above the sequence re-clamp to valid divisors.
    assert derive_blocks(16, 256, block_q=1024, block_k=1024) == (16, 256)
    with pytest.raises(ValueError, match="sublane"):
        derive_blocks(7, 128)
    with pytest.raises(ValueError, match="lane"):
        derive_blocks(8, 64)


def _fake_tpu_devices(monkeypatch):
    import jax

    monkeypatch.setattr(
        jax, "devices",
        lambda backend=None: [types.SimpleNamespace(platform="tpu")])


def test_flash_bench_fallback_flag_on_value_error(monkeypatch):
    """A kernel shape rejection is reported as an explicit
    {"fallback": true, "reason": ...} detail — the lane never fabricates
    a TFLOP/s number from a failed run."""
    import bench
    from ray_tpu.ops import flash_attention as fa_mod

    _fake_tpu_devices(monkeypatch)

    def reject(*a, **k):
        raise ValueError("no divisor aligned to the TPU lane tile")

    monkeypatch.setattr(fa_mod, "flash_attention", reject)
    results, details = {}, {}
    bench._bench_flash_attention(results, details)
    assert "flash_attention_tflops" not in results
    assert details["flash_attention"]["fallback"] is True
    assert "lane tile" in details["flash_attention"]["reason"]


def test_flash_bench_fallback_flag_on_nonmonotonic_timing(monkeypatch):
    """A timing window where the long chain is not slower than the short
    one (noise-dominated link) must yield the fallback flag, NEVER a
    negative TFLOP/s (the r05 bench regression)."""
    import bench
    from ray_tpu.ops import flash_attention as fa_mod

    _fake_tpu_devices(monkeypatch)
    # Identity "kernel": traces fine on CPU so the lane reaches timing.
    monkeypatch.setattr(fa_mod, "flash_attention",
                        lambda q, k, v, causal=True: q)
    # Frozen clock: every measured duration is 0 -> per_call <= 0.
    monkeypatch.setattr(bench.time, "perf_counter", lambda: 0.0)
    results, details = {}, {}
    bench._bench_flash_attention(results, details)
    assert "flash_attention_tflops" not in results
    assert details["flash_attention"]["fallback"] is True
    assert "non-monotonic" in details["flash_attention"]["reason"]
