"""Serve: deployments, HTTP ingress, load balancing, composition,
batching, autoscaling, rolling redeploy.

reference tests: python/ray/serve/tests/test_standalone.py,
test_deploy.py, test_autoscaling_policy.py, test_batching.py,
test_model_composition.py.
"""

import json
import os
import socket
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _http(url, data=None, timeout=30):
    req = urllib.request.Request(url, data=data)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.read()


@pytest.fixture
def serve_shutdown():
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def test_deploy_function_and_http(serve_shutdown):
    ray_tpu.init(num_cpus=4)

    @serve.deployment
    def hello(request):
        name = request.query_params.get("name", "world")
        return {"hello": name}

    port = _free_port()
    handle = serve.run(hello.bind(), port=port)
    # handle path
    assert handle.remote(serve.Request(query={"name": "via-handle"})).result() \
        == {"hello": "via-handle"}
    # HTTP path
    out = json.loads(_http(f"http://127.0.0.1:{port}/?name=tpu"))
    assert out == {"hello": "tpu"}


def test_class_deployment_load_balanced(serve_shutdown):
    ray_tpu.init(num_cpus=4)

    @serve.deployment(num_replicas=2, ray_actor_options={"num_cpus": 1})
    class Counter:
        def __init__(self):
            self.pid = os.getpid()

        def __call__(self, request):
            return {"pid": self.pid}

    port = _free_port()
    serve.run(Counter.bind(), port=port)
    pids = set()
    for _ in range(30):
        out = json.loads(_http(f"http://127.0.0.1:{port}/"))
        pids.add(out["pid"])
    assert len(pids) == 2, "requests were not balanced across both replicas"


def test_model_composition(serve_shutdown):
    ray_tpu.init(num_cpus=4)

    @serve.deployment
    class Doubler:
        def double(self, x):
            return x * 2

    @serve.deployment
    class Ingress:
        def __init__(self, doubler):
            self.doubler = doubler

        def __call__(self, request):
            x = int(request.query_params.get("x", "1"))
            return {"doubled": self.doubler.double.remote(x).result()}

    port = _free_port()
    serve.run(Ingress.bind(Doubler.bind()), port=port)
    out = json.loads(_http(f"http://127.0.0.1:{port}/?x=21"))
    assert out == {"doubled": 42}


def test_batching(serve_shutdown):
    ray_tpu.init(num_cpus=4)

    @serve.deployment(max_ongoing_requests=32)
    class Batcher:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.2)
        async def handle_batch(self, items):
            self.batch_sizes.append(len(items))
            return [i * 10 for i in items]

        async def __call__(self, request):
            x = int(request.query_params.get("x", "0"))
            return {"out": await self.handle_batch(x),
                    "batches": list(self.batch_sizes)}

    handle = serve.run(Batcher.bind(), port=_free_port())
    # Fire 8 concurrent handle calls; they must coalesce into few batches.
    resps = [handle.remote(serve.Request(query={"x": str(i)}))
             for i in range(8)]
    outs = [r.result() for r in resps]
    assert sorted(o["out"] for o in outs) == [i * 10 for i in range(8)]
    max_batch = max(max(o["batches"]) for o in outs)
    assert max_batch >= 4, f"batching did not coalesce: {outs}"


def test_autoscaling_up(serve_shutdown):
    ray_tpu.init(num_cpus=4)

    @serve.deployment(
        autoscaling_config={"min_replicas": 1, "max_replicas": 3,
                            "target_ongoing_requests": 1},
        ray_actor_options={"num_cpus": 0.5},
        max_ongoing_requests=16)
    class Slow:
        def __call__(self, request):
            time.sleep(1.0)
            return {"pid": os.getpid()}

    handle = serve.run(Slow.bind(), port=_free_port())
    assert serve.status()["Slow"]["ready"] == 1
    # Sustained concurrent load -> controller must scale up.
    resps = [handle.remote(serve.Request()) for _ in range(12)]
    deadline = time.monotonic() + 30
    scaled = False
    while time.monotonic() < deadline:
        if serve.status()["Slow"]["ready"] >= 2:
            scaled = True
            break
        time.sleep(0.2)
    for r in resps:
        r.result(timeout_s=60)
    assert scaled, "autoscaler never scaled up under sustained load"


def test_rolling_redeploy_no_drop(serve_shutdown):
    ray_tpu.init(num_cpus=4)

    def make(version):
        @serve.deployment(name="app", num_replicas=2,
                          ray_actor_options={"num_cpus": 0.5},
                          version=version)
        class App:
            def __call__(self, request):
                return {"version": version}

        return App

    port = _free_port()
    serve.run(make("v1").bind(), port=port)
    seen, errors = set(), 0
    # redeploy mid-traffic
    import threading

    stop = threading.Event()

    def traffic():
        nonlocal errors
        while not stop.is_set():
            try:
                out = json.loads(_http(f"http://127.0.0.1:{port}/", timeout=10))
                seen.add(out["version"])
            except Exception:
                errors += 1
            time.sleep(0.02)

    t = threading.Thread(target=traffic)
    t.start()
    time.sleep(0.5)
    serve.run(make("v2").bind(), port=port)
    deadline = time.monotonic() + 20
    while "v2" not in seen and time.monotonic() < deadline:
        time.sleep(0.1)
    stop.set()
    t.join()
    assert "v1" in seen and "v2" in seen
    assert errors == 0, f"{errors} requests dropped during rolling redeploy"


def test_replica_death_recovery(serve_shutdown):
    """A dead replica must leave the routing table (health check) and be
    replaced by the reconciler; traffic keeps succeeding."""
    ray_tpu.init(num_cpus=4)

    @serve.deployment(num_replicas=2, ray_actor_options={"num_cpus": 0.5})
    class P:
        def __call__(self, request):
            return {"pid": os.getpid()}

    port = _free_port()
    handle = serve.run(P.bind(), port=port)
    first = json.loads(_http(f"http://127.0.0.1:{port}/"))["pid"]
    # Kill one replica process out from under serve.
    os.kill(first, 9)
    deadline = time.monotonic() + 30
    pids = set()
    while time.monotonic() < deadline:
        try:
            pids.add(json.loads(_http(f"http://127.0.0.1:{port}/"))["pid"])
        except Exception:
            pass  # transient while the dead replica is being evicted
        if len(pids - {first}) >= 2:
            break
        time.sleep(0.2)
    assert len(pids - {first}) >= 2, (
        f"replacement replica never appeared: {pids}")
    # Steady state: requests no longer fail.
    for _ in range(10):
        out = json.loads(_http(f"http://127.0.0.1:{port}/"))
        assert out["pid"] != first


def test_multiplexed_models(serve_shutdown):
    ray_tpu.init(num_cpus=4)
    """@serve.multiplexed: per-replica LRU of loaded models, requests routed
    by model id with cache locality (reference serve/multiplex.py +
    handle.options(multiplexed_model_id=...))."""
    from ray_tpu import serve

    @serve.deployment(num_replicas=2)
    class Multi:
        def __init__(self):
            self.loads = []

        @serve.multiplexed(max_num_models_per_replica=2)
        async def get_model(self, model_id: str):
            self.loads.append(model_id)
            return f"model::{model_id}"

        async def __call__(self, request):
            mid = serve.get_multiplexed_model_id()
            model = await self.get_model(mid)
            return {"model": model, "loads": list(self.loads)}

        async def loads_so_far(self):
            return list(self.loads)

    handle = serve.run(Multi.bind(), port=_free_port())
    # 6 calls for model a, 6 for b: with cache locality each model should be
    # loaded on at most... the first call pins it to one replica; repeats
    # reuse it.
    outs_a = [handle.options(multiplexed_model_id="a").remote(None).result(
        timeout_s=60) for _ in range(6)]
    outs_b = [handle.options(multiplexed_model_id="b").remote(None).result(
        timeout_s=60) for _ in range(6)]
    assert all(o["model"] == "model::a" for o in outs_a)
    assert all(o["model"] == "model::b" for o in outs_b)
    # Cache locality: total loads of each model across replicas == 1 (every
    # later request for the model hit the replica that already had it).
    all_loads = [o["loads"] for o in outs_a + outs_b]
    final = max(all_loads, key=len)
    assert final.count("a") <= 1 or final.count("b") <= 1
    # LRU eviction: push a third model through the same replica repeatedly
    for mid in ("c", "d", "e"):
        out = handle.options(multiplexed_model_id=mid).remote(None).result(
            timeout_s=60)
        assert out["model"] == f"model::{mid}"


def test_grpc_ingress_unary_and_stream(ray_start_4cpu):
    """gRPC ingress (reference gRPCProxy): unary calls and server-streaming
    responses through the generic /ray_tpu.serve.<dep>/<method> surface."""
    import pickle

    import grpc

    @serve.deployment(name="echo")
    class Echo:
        def __call__(self, request):
            return {"got": request.body.decode(), "via": request.method}

        def shout(self, request):
            return request.body.decode().upper()

        def counted(self, request):
            n = int(request.body or b"3")
            for i in range(n):
                yield {"i": i}

    serve.run(Echo.bind(), route_prefix="/echo", port=_free_port(),
              grpc_port=0)
    try:
        gport = serve.get_grpc_port()
        assert gport
        chan = grpc.insecure_channel(f"127.0.0.1:{gport}")
        ident = lambda b: b  # raw-bytes (de)serializers

        call = chan.unary_unary("/ray_tpu.serve.echo/__call__",
                                request_serializer=ident,
                                response_deserializer=ident)
        out = pickle.loads(call(b"hello", timeout=60))
        assert out == {"got": "hello", "via": "GRPC"}

        shout = chan.unary_unary("/ray_tpu.serve.echo/shout",
                                 request_serializer=ident,
                                 response_deserializer=ident)
        assert pickle.loads(shout(b"quiet", timeout=60)) == "QUIET"

        stream = chan.unary_stream("/ray_tpu.serve.echo/countedStream",
                                   request_serializer=ident,
                                   response_deserializer=ident)
        items = [pickle.loads(b) for b in stream(b"4", timeout=120)]
        assert items == [{"i": 0}, {"i": 1}, {"i": 2}, {"i": 3}]

        # unknown deployment -> UNIMPLEMENTED
        bad = chan.unary_unary("/ray_tpu.serve.nope/__call__",
                               request_serializer=ident,
                               response_deserializer=ident)
        with pytest.raises(grpc.RpcError):
            bad(b"", timeout=30)
        chan.close()
    finally:
        serve.shutdown()


def test_autoscale_from_zero_and_back(ray_start_4cpu):
    """min_replicas=0: the deployment idles at ZERO replicas, a request
    wakes it (router demand -> controller scale-from-zero), and it drains
    back to zero after the traffic stops."""

    @serve.deployment(name="z", autoscaling_config={
        "min_replicas": 0, "max_replicas": 2, "target_ongoing_requests": 2})
    class Z:
        def __call__(self, request=None):
            return "up"

    serve.run(Z.bind(), route_prefix="/z", port=_free_port())
    try:
        h = serve.get_deployment_handle("z")
        # first request scales from zero (assign blocks until a replica is up)
        assert h.remote().result(timeout_s=90) == "up"
        # drains back to zero once idle (downscale patience x autoscale tick)
        deadline = time.time() + 60
        while time.time() < deadline:
            st = serve.status()["z"]
            if st["ready"] == 0 and st["target"] == 0:
                break
            time.sleep(0.5)
        st = serve.status()["z"]
        assert st["target"] == 0 and st["ready"] == 0, st
        # wakes again
        assert h.remote().result(timeout_s=90) == "up"
    finally:
        serve.shutdown()


def test_autoscale_target_latency(ray_start_4cpu):
    """target_latency_ms scales up when observed latency exceeds the
    target even though ongoing-requests alone would not."""

    @serve.deployment(name="slowpoke", autoscaling_config={
        "min_replicas": 1, "max_replicas": 3,
        "target_ongoing_requests": 100,  # ongoing policy would never scale
        "target_latency_ms": 30})
    class Slow:
        def __call__(self, request=None):
            time.sleep(0.12)  # 120ms >> 30ms target
            return "ok"

    serve.run(Slow.bind(), route_prefix="/slow", port=_free_port())
    try:
        h = serve.get_deployment_handle("slowpoke")
        # sustain some traffic so the latency EMA materializes
        deadline = time.time() + 60
        scaled = False
        while time.time() < deadline and not scaled:
            resps = [h.remote() for _ in range(4)]
            for r in resps:
                assert r.result(timeout_s=60) == "ok"
            scaled = serve.status()["slowpoke"]["target"] >= 3
        assert scaled, serve.status()
    finally:
        serve.shutdown()


# --------------------------------------------------------------- admission
def test_admission_shed_typed_and_http_429(serve_shutdown):
    """Overload & admission control: with the concurrency cap and queue
    both full, excess requests shed a typed BackPressureError (handle
    path) / 429 + Retry-After (HTTP path) promptly — shed, not stalled."""
    from ray_tpu.exceptions import BackPressureError

    ray_tpu.init(num_cpus=4)

    @serve.deployment(max_ongoing_requests=1, max_queued_requests=0)
    class Slow:
        def __call__(self, request=None):
            if getattr(request, "path", "").rstrip("/").endswith("/stats"):
                return {"pid": os.getpid()}
            time.sleep(3.0)
            return "done"

    port = _free_port()
    handle = serve.run(Slow.bind(), port=port)
    first = handle.remote()  # occupies the only executing slot
    time.sleep(0.3)
    # Handle path: this router's slot table is full -> immediate
    # queue_full shed (max_queued_requests=0 means no waiting room).
    t0 = time.monotonic()
    with pytest.raises(BackPressureError) as ei:
        handle.remote()
    shed_s = time.monotonic() - t0
    assert ei.value.reason == "queue_full"
    assert ei.value.deployment == "Slow"
    assert ei.value.retry_after_s > 0
    assert shed_s < 1.0, f"queue-full shed took {shed_s:.2f}s"
    # HTTP path: the proxy's router dispatches (its own slot table is
    # empty), the replica's hard cap rejects, the retry budget burns out
    # -> 429 with Retry-After, typed JSON body.
    err = None
    try:
        _http(f"http://127.0.0.1:{port}/", timeout=20)
    except urllib.error.HTTPError as e:
        err = e
    assert err is not None, "overloaded request should not succeed"
    assert err.code == 429, err.code
    assert int(err.headers["Retry-After"]) >= 1
    body = json.loads(err.read())
    assert body["error"]["type"] == "BackPressureError"
    assert body["error"]["reason"] in ("queue_full", "replica_busy")
    # Stats stay readable exactly while the deployment is saturated, and
    # the proxy merges router admission stats under "serve".
    st = json.loads(_http(f"http://127.0.0.1:{port}/stats", timeout=20))
    assert "serve" in st, st
    assert st["serve"]["max_ongoing_requests"] == 1
    assert st["serve"]["max_queued_requests"] == 0
    assert st["serve"]["shed_total"] >= 1
    assert first.result(timeout_s=30) == "done"


def test_admission_off_pins_legacy_behavior(serve_shutdown, monkeypatch):
    """RT_SERVE_ADMISSION=0 restores the pre-admission plane: the routing
    frame carries no budgets key, stats responses gain no serve key, and
    budgets that WOULD shed are inert (requests queue and succeed)."""
    monkeypatch.setenv("RT_SERVE_ADMISSION", "0")
    ray_tpu.init(num_cpus=4)

    @serve.deployment(max_ongoing_requests=1, max_queued_requests=0)
    class Slow:
        def __call__(self, request=None):
            if getattr(request, "path", "").rstrip("/").endswith("/stats"):
                return {"pid": os.getpid()}
            time.sleep(0.3)
            return "ok"

    port = _free_port()
    handle = serve.run(Slow.bind(), port=port)
    from ray_tpu.serve._private.controller import CONTROLLER_NAME
    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    frame = ray_tpu.get(controller.get_routing.remote("Slow", -1, 0.0))
    assert "budgets" not in frame, frame
    # With the plane off these WOULD-shed requests all queue and succeed.
    resps = [handle.remote() for _ in range(4)]
    assert [r.result(timeout_s=60) for r in resps] == ["ok"] * 4
    st = json.loads(_http(f"http://127.0.0.1:{port}/stats"))
    assert "serve" not in st, st


def test_admission_queued_client_disconnect_frees_slot(serve_shutdown):
    """A client that disconnects while its request is still QUEUED must
    release the queue slot (cancel event -> QueueCancelled) so the queue
    drains to zero while the occupying request is still executing."""
    ray_tpu.init(num_cpus=4)

    @serve.deployment(max_ongoing_requests=1, max_queued_requests=4,
                      queue_deadline_s=30.0)
    class Slow:
        def __call__(self, request=None):
            if getattr(request, "path", "").rstrip("/").endswith("/stats"):
                return {"pid": os.getpid()}
            time.sleep(4.0)
            return "done"

    port = _free_port()
    serve.run(Slow.bind(), port=port)
    # Occupy the slot THROUGH THE PROXY so its router's slot table (the
    # one the raw-socket request below queues against) is full.
    import threading

    first_result = {}

    def _first():
        first_result["body"] = _http(f"http://127.0.0.1:{port}/", timeout=30)

    t = threading.Thread(target=_first, daemon=True)
    t.start()
    time.sleep(0.5)

    def queued_depth():
        st = json.loads(_http(f"http://127.0.0.1:{port}/stats", timeout=10))
        return st["serve"]["queued"]

    # Raw socket: send a request that will park in the admission queue,
    # then slam the connection shut while it is still queued.
    s = socket.create_connection(("127.0.0.1", port), timeout=5)
    s.sendall(b"GET /work HTTP/1.1\r\nHost: x\r\nConnection: close\r\n\r\n")
    deadline = time.time() + 10
    while queued_depth() < 1 and time.time() < deadline:
        time.sleep(0.05)
    assert queued_depth() >= 1, "request never reached the queue"
    s.close()  # client gone; its queue slot must free promptly
    deadline = time.time() + 10
    while queued_depth() > 0 and time.time() < deadline:
        time.sleep(0.05)
    assert queued_depth() == 0, "disconnected client left a queue slot"
    t.join(timeout=30)
    assert first_result.get("body") == b"done"
