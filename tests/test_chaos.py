"""Deterministic chaos tests: node-liveness suspicion + incarnation fencing.

Parity target: reference GCS node-failure semantics — a transient raylet
connection loss does NOT declare the node dead (health checks tolerate a
reconnect window), and registration epochs fence messages from a node's
previous life. The rpc.FaultInjector severs/drops frames on named
connection classes so the blips are reproducible in-process:

- a controller<->agent blip SHORTER than the suspicion grace window must
  produce ZERO duplicate actor instances (the actor's direct pipe serves
  uninterrupted across the blip);
- a blip LONGER than the window runs the existing death/restart path, and
  a late-returning zombie instance is reaped;
- a stale-incarnation agent message is rejected and logged.
"""

import json
import os
import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu._private import rpc
from ray_tpu._private.ids import NodeID
from ray_tpu._private.resources import ResourceSet


def _spawn_agent(controller_addr: str, session: str, num_cpus=2):
    env = dict(os.environ)
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    driver_paths = [p for p in sys.path if p and os.path.exists(p)]
    env["PYTHONPATH"] = os.pathsep.join([pkg_root] + driver_paths)
    node_id = NodeID.from_random().hex()
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.node_agent",
         "--controller", controller_addr,
         "--node-id", node_id,
         "--session", session,
         "--resources",
         json.dumps(ResourceSet({"CPU": float(num_cpus)}).raw())],
        env=env)
    return node_id, proc


def _snapshot():
    return ray_tpu._private.worker.global_worker().state_snapshot()


def _wait(pred, timeout: float, what: str):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise TimeoutError(f"timed out waiting for {what}")


def _controller():
    return ray_tpu._head.controller


def _start_chaos_cluster(grace: float, agents: int = 1, agent_cpus=2):
    """In-process head (0 CPUs, so work lands on the agents) + subprocess
    agents whose controller connections the injector can sever."""
    ray_tpu.init(num_cpus=0, _system_config={
        "fault_injection": True,
        "node_suspect_grace_s": grace,
    })
    head = ray_tpu._head
    addr = f"{head.controller_addr[0]}:{head.controller_addr[1]}"
    spawned = [_spawn_agent(addr, head.session_id, num_cpus=agent_cpus)
               for _ in range(agents)]
    for nid, _proc in spawned:
        _wait(lambda: (_snapshot()["nodes"].get(nid) or {}).get("alive"),
              60, f"node {nid[:8]} to register")
    return spawned


@pytest.fixture
def chaos_cleanup():
    procs = []
    yield procs
    try:
        ray_tpu.shutdown()
    except Exception:
        pass
    for proc in procs:
        try:
            proc.kill()
        except Exception:
            pass
    inj = rpc.fault_injector()
    if inj is not None:
        inj.clear()
    rpc.disable_fault_injection()


@ray_tpu.remote(num_cpus=1, max_restarts=1)
class Counter:
    def __init__(self):
        self.n = 0
        import time as _t

        self.born = _t.time()

    def bump(self):
        self.n += 1
        return self.n

    def ident(self):
        return {"pid": os.getpid(), "node": os.environ.get("RT_NODE_ID")}


def test_conn_blip_shorter_than_grace_no_duplicate_actor(chaos_cleanup):
    """Sever the controller<->agent link, let the agent reconnect within
    the grace window: the node goes SUSPECT and back to ALIVE, the actor is
    never restarted, and its pipe serves calls throughout the blip."""
    spawned = _start_chaos_cluster(grace=8.0)
    chaos_cleanup.extend(p for _n, p in spawned)
    nid, _proc = spawned[0]

    a = Counter.remote()
    assert ray_tpu.get(a.bump.remote(), timeout=60) == 1
    before = ray_tpu.get(a.ident.remote(), timeout=60)
    assert before["node"] == nid
    ctrl = _controller()
    ent = ctrl.actors[a._actor_id]
    instance_before = ent.instance
    inc_before = _snapshot()["nodes"][nid]["incarnation"]

    inj = rpc.fault_injector()
    assert inj is not None
    n = inj.sever("node", match=lambda c: c.meta.get("node_id") == nid)
    assert n == 1

    # The node goes SUSPECT (frozen, unschedulable, actor NOT restarted)
    # until the agent's reconnect lands as a new incarnation.
    def _blipped():
        n = _snapshot()["nodes"][nid]
        return n["liveness"] == "SUSPECT" or n["incarnation"] > inc_before

    _wait(_blipped, 10, "node to enter SUSPECT")

    # The actor's direct pipe never touched the severed link: calls keep
    # working DURING the blip.
    assert ray_tpu.get(a.bump.remote(), timeout=30) == 2

    # Agent reconnects within grace: node returns ALIVE as a new
    # incarnation, reconciled in place.
    _wait(lambda: _snapshot()["nodes"][nid]["alive"]
          and _snapshot()["nodes"][nid]["incarnation"] > inc_before,
          30, "node to reconcile back to ALIVE")

    after = ray_tpu.get(a.ident.remote(), timeout=60)
    snap = _snapshot()
    assert after["pid"] == before["pid"], "duplicate actor instance spawned"
    assert snap["actors"][a._actor_id]["state"] == "ALIVE"
    assert snap["actors"][a._actor_id]["restarts_used"] == 0
    assert ent.instance == instance_before
    # State survived: the counter kept its increments across the blip.
    assert ray_tpu.get(a.bump.remote(), timeout=30) == 3
    # New work schedules on the reconciled node again.
    @ray_tpu.remote(num_cpus=1)
    def where():
        return os.environ.get("RT_NODE_ID")

    assert ray_tpu.get(where.remote(), timeout=60) == nid

    # kill() DURING a blip cannot reach the agent; the reconcile's
    # inventory sweep must reap the zombie instance once the node returns.
    b = Counter.remote()
    b_pid = ray_tpu.get(b.ident.remote(), timeout=60)["pid"]
    inc2 = _snapshot()["nodes"][nid]["incarnation"]
    assert inj.sever("node", match=lambda c: c.meta.get("node_id") == nid) == 1
    ray_tpu.kill(b)
    _wait(lambda: _snapshot()["nodes"][nid]["alive"]
          and _snapshot()["nodes"][nid]["incarnation"] > inc2,
          30, "node to reconcile after second blip")

    def _killed_instance_gone():
        try:
            os.kill(b_pid, 0)
            return False
        except OSError:
            return True

    _wait(_killed_instance_gone, 20, "kill()ed-during-blip zombie to be reaped")


def test_conn_blip_during_actor_creation(chaos_cleanup):
    """Blip while an actor's __init__ is still running on the node: the
    creation completes through the outage (the worker reports on its own
    connection) and exactly one instance exists afterwards."""
    spawned = _start_chaos_cluster(grace=10.0)
    chaos_cleanup.extend(p for _n, p in spawned)
    nid, _proc = spawned[0]

    @ray_tpu.remote(num_cpus=1, max_restarts=1)
    class Slow:
        def __init__(self):
            import time as _t

            _t.sleep(2.5)
            self.pid = os.getpid()

        def ident(self):
            return self.pid

    s = Slow.remote()
    ctrl = _controller()
    # Deterministic cut point: the creation was dispatched (worker bound)
    # but __init__ has not finished.
    _wait(lambda: ctrl.actors[s._actor_id].worker_id is not None,
          60, "actor creation to dispatch")
    assert ctrl.actors[s._actor_id].state == "PENDING"
    inj = rpc.fault_injector()
    assert inj.sever("node", match=lambda c: c.meta.get("node_id") == nid) == 1

    pid = ray_tpu.get(s.ident.remote(), timeout=90)
    assert pid == ray_tpu.get(s.ident.remote(), timeout=30)
    snap = _snapshot()
    assert snap["actors"][s._actor_id]["state"] == "ALIVE"
    assert snap["actors"][s._actor_id]["restarts_used"] == 0
    _wait(lambda: _snapshot()["nodes"][nid]["alive"], 30,
          "node to reconcile back to ALIVE")


def test_conn_blip_longer_than_grace_runs_death_path(chaos_cleanup):
    """Keep the agent out past the grace window (its re-register frame is
    dropped once): the node is promoted SUSPECT -> DEAD, the actor restarts
    on the surviving node, and when the original agent finally returns its
    stale instance is reaped — exactly one instance lives."""
    spawned = _start_chaos_cluster(grace=1.5, agents=2)
    chaos_cleanup.extend(p for _n, p in spawned)

    a = Counter.remote()
    assert ray_tpu.get(a.bump.remote(), timeout=60) == 1
    before = ray_tpu.get(a.ident.remote(), timeout=60)
    host_nid = before["node"]
    other_nid = next(n for n, _p in spawned if n != host_nid)

    inj = rpc.fault_injector()
    # The agent reconnects in ~0.5s — well inside the window. Sever its
    # next few re-register attempts (each fails fast and retries 0.5s
    # later), keeping the node out past the 1.5s grace.
    inj.add_rule(
        None, "sever", direction="recv", methods={"register"}, times=4,
        match=lambda m: (m.get("a") or {}).get("kind") == "node"
        and m["a"].get("node_id") == host_nid)
    assert inj.sever(
        "node", match=lambda c: c.meta.get("node_id") == host_nid) == 1

    # Grace expires -> death path: the actor restarts on the OTHER node.
    _wait(lambda: _snapshot()["actors"][a._actor_id]["restarts_used"] == 1,
          30, "actor to restart after grace expiry")
    _wait(lambda: _snapshot()["actors"][a._actor_id]["state"] == "ALIVE",
          60, "restarted actor to come up")

    # The original agent eventually re-registers (fresh node incarnation)
    # and its resurfaced stale instance gets killed: the old pid dies.
    # (Until then the driver's existing pipe still points at the zombie —
    # the reap is what collapses the split brain.)
    _wait(lambda: (_snapshot()["nodes"].get(host_nid) or {}).get("alive"),
          60, "blipped agent to rejoin")

    def _old_instance_gone():
        try:
            os.kill(before["pid"], 0)
            return False
        except OSError:
            return True

    _wait(_old_instance_gone, 30, "zombie actor instance to be reaped")

    # With the zombie gone, the handle re-resolves to the restarted
    # instance: fresh pid on the surviving node, fresh in-memory state.
    after = ray_tpu.get(a.ident.remote(), timeout=60)
    assert after["node"] == other_nid
    assert after["pid"] != before["pid"]
    assert ray_tpu.get(a.bump.remote(), timeout=30) == 1


# --------------------------------------------------------------------------
# Transport-level: coalesced writes must keep FaultInjector PER-LOGICAL-FRAME
# semantics (drop/delay/dup/sever apply to individual frames, not to the
# coalesced byte blob) and preserve strict per-connection ordering.


class _XportHarness:
    """Raw RpcServer + client Connection over a real socket (the in-process
    LocalConnection bypass is disabled so frames actually ride the
    coalescing write buffer)."""

    def __init__(self, label="xport"):
        self.got: list = []   # push payloads in arrival order
        self.reqs: list = []  # request payloads in arrival order
        self.io = rpc.EventLoopThread(name="xport-srv")
        self.cio = rpc.EventLoopThread(name="xport-cli")

        async def on_req(conn, method, a):
            self.reqs.append(a["i"])
            return a["i"]

        async def on_push(conn, method, a):
            self.got.append(a["i"])

        self.server = rpc.RpcServer(on_req, on_push)
        port = self.io.run(self.server.start("127.0.0.1", 0))
        rpc._LOCAL_SERVERS.pop(port, None)  # force the socket path
        self.conn = self.cio.run(rpc.connect("127.0.0.1", port, label=label))

    def burst(self, n, method="p"):
        async def _go():
            errors = []
            for i in range(n):
                try:
                    await self.conn.push(method, i=i)
                except rpc.ConnectionClosed:
                    errors.append(i)
            return errors

        return self.cio.run(_go(), timeout=30)

    def close(self):
        for fn in (lambda: self.cio.run(self.conn.close(), timeout=5),
                   lambda: self.io.run(self.server.stop(), timeout=5)):
            try:
                fn()
            except Exception:
                pass
        self.cio.stop()
        self.io.stop()


@pytest.fixture
def xport_injector():
    inj = rpc.enable_fault_injection()
    inj.clear()
    yield inj
    inj.clear()
    rpc.disable_fault_injection()


def test_coalesced_burst_drop_exactly_one_frame(xport_injector):
    """A drop rule must remove exactly ONE logical frame from a burst that
    rides a coalesced write — not the whole coalesced blob."""
    h = _XportHarness()
    try:
        rule = xport_injector.add_rule(
            "xport", "drop", direction="send", methods={"p"},
            after=3, times=1)
        assert h.burst(10) == []
        _wait(lambda: len(h.got) >= 9, 15, "burst delivery")
        time.sleep(0.2)  # no straggler may follow
        assert h.got == [0, 1, 2, 4, 5, 6, 7, 8, 9]
        assert rule.applied == 1
    finally:
        h.close()


def test_coalesced_burst_sever_mid_burst_stops_later_frames(xport_injector):
    """Sever landing on frame k of a coalesced burst kills the connection:
    frames after k are NEVER delivered (earlier frames may be lost with the
    reset too, but whatever arrives is an in-order prefix), and the sender
    observes ConnectionClosed from the severed frame on."""
    h = _XportHarness()
    try:
        xport_injector.add_rule(
            "xport", "sever", direction="send", methods={"p"}, after=5)
        errors = h.burst(10)
        assert errors and min(errors) == 5, errors
        time.sleep(0.3)
        assert all(i < 5 for i in h.got), f"post-sever frame delivered: {h.got}"
        assert h.got == sorted(h.got)
        _wait(lambda: h.conn.closed, 10, "client side to observe the close")
    finally:
        h.close()


def test_coalesced_burst_dup_and_delay_per_frame(xport_injector):
    """dup duplicates exactly one logical frame in place; a delayed frame
    holds up YOUNGER frames (per-connection ordering survives — TCP cannot
    reorder, so neither may the injector under coalescing)."""
    h = _XportHarness()
    try:
        rule = xport_injector.add_rule(
            "xport", "dup", direction="send", methods={"p"},
            after=2, times=1)
        assert h.burst(6) == []
        _wait(lambda: len(h.got) >= 7, 15, "dup burst delivery")
        assert h.got == [0, 1, 2, 2, 3, 4, 5]
        assert rule.applied == 1

        xport_injector.clear()
        h.got.clear()
        rule = xport_injector.add_rule(
            "xport", "delay", direction="send", methods={"p"},
            after=2, times=1, delay_s=0.25)
        assert h.burst(6) == []
        _wait(lambda: len(h.got) >= 6, 15, "delayed burst delivery")
        assert h.got == [0, 1, 2, 3, 4, 5], "delay reordered the burst"
        assert rule.applied == 1
    finally:
        h.close()


def test_hang_holds_stream_without_closing(xport_injector):
    """'hang' vs 'drop' distinction: drop removes ONE frame and later
    frames still flow; hang holds the matched frame AND everything behind
    it forever while the socket stays healthy (neither side observes a
    close) — the silent-stall chaos primitive."""
    h = _XportHarness()
    try:
        rule = xport_injector.add_rule(
            "xport", "hang", direction="send", methods={"p"}, after=3)
        assert h.burst(8) == []  # pushes buffer fine; nothing errors
        time.sleep(0.5)
        # Only the pre-hang prefix arrives; the held frame and everything
        # younger never do.
        assert h.got == [0, 1, 2], h.got
        assert rule.applied >= 1
        # The connection is NOT closed — that's what distinguishes a hang
        # from a sever: liveness machinery keyed on connection close (PR 2)
        # never fires.
        assert not h.conn.closed
        time.sleep(0.3)
        assert h.got == [0, 1, 2]
    finally:
        h.close()


def test_drop_vs_hang_on_local_transport(xport_injector):
    """Same distinction on the in-process LocalConnection transport: a
    dropped request errors its reply future; a hung one never resolves
    (and later frames wedge behind it) with the link still 'healthy'."""
    import asyncio

    io = rpc.EventLoopThread(name="local-srv")

    async def on_req(conn, method, a):
        return a["i"]

    server = rpc.RpcServer(on_req, None)
    port = io.run(server.start("127.0.0.1", 0))
    cio = rpc.EventLoopThread(name="local-cli")
    try:
        conn = cio.run(rpc.connect("127.0.0.1", port, label="loc"))
        assert isinstance(conn, rpc.LocalConnection)
        # drop: the reply future fails fast (frame provably gone).
        xport_injector.add_rule("loc", "drop", direction="send",
                                methods={"m"}, times=1)
        try:
            cio.run(conn.call("m", i=1), timeout=5)
            raise AssertionError("dropped call resolved")
        except rpc.ConnectionClosed:
            pass
        assert cio.run(conn.call("m", i=2), timeout=5) == 2  # later frames flow
        # hang: the call never resolves, the link never closes, and later
        # frames wedge behind the held one.
        xport_injector.add_rule("loc", "hang", direction="send",
                                methods={"m"})

        async def hung_call():
            try:
                await asyncio.wait_for(conn.call("m", i=3), 0.8)
                return "resolved"
            except asyncio.TimeoutError:
                return "hung"

        assert cio.run(hung_call(), timeout=10) == "hung"
        assert not conn.closed
        assert cio.run(hung_call(), timeout=10) == "hung"  # wedged behind
    finally:
        try:
            io.run(server.stop(), timeout=5)
        except Exception:
            pass
        cio.stop()
        io.stop()


def test_call_start_pipelined_ordering_survives_coalescing(xport_injector):
    """call_start's contract — requests hit the peer in issue order while
    replies overlap — must hold when the frames ride one coalesced write."""
    h = _XportHarness()
    try:
        async def pipeline():
            import asyncio

            futs = [await h.conn.call_start("m", i=i) for i in range(50)]
            return await asyncio.gather(*futs)

        res = h.cio.run(pipeline(), timeout=30)
        assert list(res) == list(range(50))
        assert h.reqs == list(range(50)), "requests arrived out of order"
    finally:
        h.close()


def test_stale_incarnation_message_rejected(chaos_cleanup):
    """A zombie agent from a previous life of a node pushes heartbeats and
    worker_died with its old incarnation: the controller rejects and logs
    them, and the old connection's close is not a liveness event for the
    current life."""
    ray_tpu.init(num_cpus=1, _system_config={
        "fault_injection": True,
        "node_suspect_grace_s": 5.0,
    })
    ctrl = _controller()
    addr = ray_tpu._head.controller_addr
    io = rpc.EventLoopThread(name="zombie-io")
    nid = "zombie" + NodeID.from_random().hex()[:8]
    try:
        async def _register():
            conn = await rpc.connect(*addr)
            rep = await conn.call(
                "register", kind="node", node_id=nid,
                address=("127.0.0.1", 1), resources={}, labels={})
            return conn, rep["incarnation"]

        old_conn, old_inc = io.run(_register(), timeout=30)
        assert old_inc == ctrl.node_incarnations[nid]
        new_conn, new_inc = io.run(_register(), timeout=30)
        assert new_inc == old_inc + 1

        rejected_before = ctrl.stale_incarnation_rejections
        io.run(old_conn.push("heartbeat", node_id=nid, incarnation=old_inc))
        io.run(old_conn.push("worker_died", worker_id="w" * 16,
                             node_id=nid, incarnation=old_inc))
        _wait(lambda: ctrl.stale_incarnation_rejections >= rejected_before + 2,
              10, "stale-incarnation messages to be rejected")

        # A current-incarnation heartbeat is accepted (no new rejections).
        count = ctrl.stale_incarnation_rejections
        io.run(new_conn.push("heartbeat", node_id=nid, incarnation=new_inc))
        time.sleep(0.3)
        assert ctrl.stale_incarnation_rejections == count
        beat_before = ctrl.nodes[nid].last_beat
        io.run(old_conn.push("heartbeat", node_id=nid, incarnation=old_inc))
        time.sleep(0.3)
        assert ctrl.nodes[nid].last_beat == beat_before, \
            "stale heartbeat refreshed liveness"

        # The PREVIOUS life's connection closing must not suspect/kill the
        # current life.
        io.run(old_conn.close(), timeout=10)
        time.sleep(0.5)
        assert ctrl.nodes[nid].liveness == "ALIVE"
        assert ctrl.nodes[nid].incarnation == new_inc
    finally:
        io.stop()
