"""Chaos: compiled dataflow graphs under stage death (README "Compiled
graphs" failure model).

Pins the acceptance behaviors of ISSUE 15: SIGKILLing ANY stage during
pipelined steady state surfaces a typed DagStageError NAMING the stage on
every in-flight DagRef within the detection deadline (never a hang), the
`dag_stage_death` event lands entity-linked in the PR 14 event plane, and
teardown after chaos leaves ZERO leaked shm channels (kill-then-unlink,
unconditionally)."""

import os
import signal
import time

import pytest

import ray_tpu
from ray_tpu.exceptions import DagStageError

DEADLINE_S = 25.0  # detection budget: runtime death detection + one poll


def _wait(pred, timeout=30.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = pred()
        if out:
            return out
        time.sleep(0.2)
    raise TimeoutError(f"timed out waiting for {what}")


def test_function_stage_sigkill_attributes_all_inflight(ray_start_4cpu):
    """Kill the MIDDLE function-stage actor of a 3-stage chain with
    several invocations in flight: every in-flight DagRef fails with
    DagStageError naming the stage and its invocation, later executes
    fail fast, the event chain lands, and teardown leaks nothing."""
    from ray_tpu.dag import InputNode, compile
    from ray_tpu.util import state

    @ray_tpu.remote
    def head(x):
        return x + 1

    @ray_tpu.remote
    def mid(x):
        time.sleep(0.2)  # hold a queue so invocations pile up in flight
        return x * 10

    @ray_tpu.remote
    def tail(x):
        return x - 1

    with InputNode() as inp:
        dag = tail.bind(mid.bind(head.bind(inp)))
    cdag = compile(dag)
    paths = [ch._path for ch in cdag._channels]
    try:
        # Healthy steady state first.
        assert cdag.execute(1).get(timeout=60) == (1 + 1) * 10 - 1
        mid_pid = ray_tpu.get(cdag._actors[1].pid.remote(), timeout=30)

        refs = [cdag.execute(i) for i in range(2, 8)]  # pipelined in flight
        t0 = time.monotonic()
        os.kill(mid_pid, signal.SIGKILL)

        for r in refs:
            with pytest.raises(DagStageError) as ei:
                r.get(timeout=DEADLINE_S + 10)
            e = ei.value
            assert e.stage and "mid" in e.stage, f"error does not name stage: {e}"
            assert e.invocation == r.seq
            assert "died" in str(e)
        detect_s = time.monotonic() - t0
        assert detect_s < DEADLINE_S, (
            f"attribution took {detect_s:.1f}s (> {DEADLINE_S}s deadline)")

        # The failure is sticky: a NEW execute fails fast and typed.
        with pytest.raises(DagStageError, match="mid"):
            cdag.execute(99)

        # Event chain: dag_stage_death entity-linked to the dag id.
        def _death_event():
            rows = state.list_events(entity=cdag.dag_id)
            return [e for e in rows if e["kind"] == "dag_stage_death"] or None

        evs = _wait(_death_event, what="dag_stage_death event")
        assert "mid" in evs[0]["attrs"]["stage"]
        assert evs[0]["sev"] == "error"
    finally:
        cdag.teardown()
    leaked = [p for p in paths if os.path.exists(p)]
    assert not leaked, f"chaos teardown leaked shm channels: {leaked}"
    # The events plane also saw the (forced) teardown.
    _wait(lambda: [e for e in state.list_events(entity=cdag.dag_id)
                   if e["kind"] == "dag_teardown"] or None,
          what="dag_teardown event")


def test_actor_method_stage_sigkill_and_loop_cancel(ray_start_4cpu):
    """Kill an EXISTING actor hosting a bound-method stage: in-flight
    refs attribute to that stage, and teardown cooperatively cancels the
    SURVIVING downstream actor's loop thread (its stop token can never
    arrive through the dead upstream) — the survivor keeps serving normal
    calls and no channel leaks."""
    from ray_tpu.dag import InputNode, compile

    @ray_tpu.remote
    class Upstream:
        def work(self, x):
            time.sleep(0.15)
            return x * 2

        def pid(self):
            return os.getpid()

    @ray_tpu.remote
    class Downstream:
        def __init__(self):
            self.seen = 0

        def post(self, x):
            self.seen += 1
            return x + 1

        def count(self):
            return self.seen

    up, down = Upstream.remote(), Downstream.remote()
    up_pid = ray_tpu.get(up.pid.remote(), timeout=60)
    with InputNode() as inp:
        dag = down.post.bind(up.work.bind(inp))
    cdag = compile(dag)
    paths = [ch._path for ch in cdag._channels]
    try:
        assert cdag.execute(3).get(timeout=60) == 7
        refs = [cdag.execute(i) for i in range(4)]
        t0 = time.monotonic()
        os.kill(up_pid, signal.SIGKILL)
        for r in refs:
            with pytest.raises(DagStageError) as ei:
                r.get(timeout=DEADLINE_S + 10)
            assert ei.value.stage and "work" in ei.value.stage
        assert time.monotonic() - t0 < DEADLINE_S
    finally:
        cdag.teardown()
    leaked = [p for p in paths if os.path.exists(p)]
    assert not leaked, f"chaos teardown leaked shm channels: {leaked}"
    # The surviving actor's loop thread was cancelled (not wedged on the
    # dead edge): it still answers normal calls promptly.
    assert ray_tpu.get(down.count.remote(), timeout=30) >= 1


def test_dead_dag_refs_never_hang_without_get(ray_start_2cpu):
    """A consumer that parked on DagRef.get BEFORE the death still gets
    the attributed error (the monitor fulfills refs; nothing depends on
    the caller polling)."""
    import threading

    from ray_tpu.dag import InputNode, compile

    @ray_tpu.remote
    def slow(x):
        time.sleep(0.3)
        return x

    with InputNode() as inp:
        dag = slow.bind(inp)
    cdag = compile(dag)
    try:
        pid = ray_tpu.get(cdag._actors[0].pid.remote(), timeout=30)
        ref = cdag.execute(1)
        got: list = []

        def consume():
            try:
                got.append(("ok", ref.get(timeout=DEADLINE_S + 10)))
            except BaseException as e:  # noqa: BLE001 - recorded for assert
                got.append(("err", e))

        t = threading.Thread(target=consume)
        t.start()
        time.sleep(0.1)  # the consumer is parked in get()
        os.kill(pid, signal.SIGKILL)
        t.join(timeout=DEADLINE_S + 15)
        assert not t.is_alive(), "get() hung past the detection deadline"
        kind, payload = got[0]
        assert kind == "err" and isinstance(payload, DagStageError), payload
    finally:
        cdag.teardown()
