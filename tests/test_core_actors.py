"""Actors (parity: reference python/ray/tests/test_actor*.py)."""

import time

import pytest

import ray_tpu
from ray_tpu.exceptions import ActorDiedError, TaskError


def test_counter_actor(ray_start_2cpu):
    @ray_tpu.remote
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def inc(self, k=1):
            self.n += k
            return self.n

        def value(self):
            return self.n

    c = Counter.remote(10)
    assert ray_tpu.get(c.inc.remote(), timeout=30) == 11
    assert ray_tpu.get(c.inc.remote(5), timeout=30) == 16
    assert ray_tpu.get(c.value.remote(), timeout=30) == 16


def test_actor_calls_ordered(ray_start_2cpu):
    @ray_tpu.remote
    class Appender:
        def __init__(self):
            self.log = []

        def add(self, x):
            self.log.append(x)
            return list(self.log)

    a = Appender.remote()
    refs = [a.add.remote(i) for i in range(10)]
    final = ray_tpu.get(refs[-1], timeout=30)
    assert final == list(range(10))


def test_named_actor_and_get_actor(ray_start_2cpu):
    @ray_tpu.remote
    class Store:
        def __init__(self):
            self.d = {}

        def set(self, k, v):
            self.d[k] = v
            return True

        def get(self, k):
            return self.d.get(k)

    s = Store.options(name="kvstore").remote()
    assert ray_tpu.get(s.set.remote("a", 1), timeout=30)
    s2 = ray_tpu.get_actor("kvstore")
    assert ray_tpu.get(s2.get.remote("a"), timeout=30) == 1


def test_get_if_exists(ray_start_2cpu):
    @ray_tpu.remote
    class Single:
        def ping(self):
            return "pong"

    a = Single.options(name="single", get_if_exists=True).remote()
    b = Single.options(name="single", get_if_exists=True).remote()
    assert a._actor_id == b._actor_id


def test_actor_method_exception(ray_start_2cpu):
    @ray_tpu.remote
    class Bad:
        def fail(self):
            raise RuntimeError("actor method failed")

    b = Bad.remote()
    with pytest.raises(TaskError, match="actor method failed"):
        ray_tpu.get(b.fail.remote(), timeout=30)


def test_actor_init_exception(ray_start_2cpu):
    @ray_tpu.remote
    class BadInit:
        def __init__(self):
            raise RuntimeError("init failed")

        def ping(self):
            return 1

    b = BadInit.remote()
    with pytest.raises((TaskError, ActorDiedError)):
        ray_tpu.get(b.ping.remote(), timeout=30)


def test_kill_actor(ray_start_2cpu):
    @ray_tpu.remote
    class Victim:
        def ping(self):
            return "pong"

    v = Victim.remote()
    assert ray_tpu.get(v.ping.remote(), timeout=30) == "pong"
    ray_tpu.kill(v)
    time.sleep(0.3)
    with pytest.raises(ActorDiedError):
        ray_tpu.get(v.ping.remote(), timeout=10)


def test_pass_handle_to_task(ray_start_2cpu):
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    @ray_tpu.remote
    def bump(c):
        return ray_tpu.get(c.inc.remote(), timeout=30)

    c = Counter.remote()
    assert ray_tpu.get(bump.remote(c), timeout=60) == 1
    assert ray_tpu.get(bump.remote(c), timeout=60) == 2
    assert ray_tpu.get(c.inc.remote(), timeout=30) == 3


def test_actor_restart(ray_start_2cpu):
    @ray_tpu.remote(max_restarts=1)
    class Flaky:
        def __init__(self):
            self.n = 0

        def pid(self):
            import os

            return os.getpid()

        def die(self):
            import os

            os._exit(1)

        def ping(self):
            return "pong"

    f = Flaky.remote()
    pid1 = ray_tpu.get(f.pid.remote(), timeout=30)
    f.die.remote()
    time.sleep(1.0)
    # After restart the actor should answer again from a new process.
    pid2 = ray_tpu.get(f.pid.remote(), timeout=60)
    assert pid2 != pid1


def test_actor_task_transparent_retry(ray_start_2cpu, tmp_path):
    """A call that dies mid-flight is retried on the restarted instance
    (parity: reference max_task_retries semantics)."""
    marker = str(tmp_path / "died_once")

    @ray_tpu.remote(max_restarts=1, max_task_retries=1)
    class DieOnce:
        def work(self, marker):
            import os

            if not os.path.exists(marker):
                open(marker, "w").close()
                os._exit(1)
            return 42

    a = DieOnce.remote()
    assert ray_tpu.get(a.work.remote(marker), timeout=60) == 42


def test_actor_fate_sharing_with_owner(ray_start_4cpu):
    """Non-detached actors created BY an actor die when their owner dies
    (reference gcs_actor_manager OnWorkerDead); detached ones survive."""

    @ray_tpu.remote
    class Child:
        def ping(self):
            return "pong"

    @ray_tpu.remote
    class Owner:
        def __init__(self):
            self.child = Child.remote()
            self.free_child = Child.options(
                name="freechild", lifetime="detached").remote()

        def handles(self):
            return self.child, self.free_child

    owner = Owner.remote()
    child, free_child = ray_tpu.get(owner.handles.remote(), timeout=60)
    assert ray_tpu.get(child.ping.remote(), timeout=60) == "pong"
    ray_tpu.kill(owner)
    # non-detached child dies with its owner
    deadline = time.time() + 30
    died = False
    while time.time() < deadline and not died:
        try:
            ray_tpu.get(child.ping.remote(), timeout=5)
            time.sleep(0.2)
        except Exception:
            died = True
    assert died, "non-detached child survived its owner"
    # detached child keeps serving
    assert ray_tpu.get(free_child.ping.remote(), timeout=60) == "pong"
