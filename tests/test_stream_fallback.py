"""Nak-fallback regression: when the push transport is pinned off
(`RT_STREAM_PUSH=0`) a replica that cannot attach the proxy's shm ring
naks the handshake and the proxy degrades to the classic per-item reply
loop — and the client-visible token stream is BYTE-IDENTICAL to the
push-transport run.

LLMConfig seeds its weights (seed=0 default), so two separate clusters
decode the same greedy continuation for the same prompt: the comparison
runs cluster A on the push transport, tears everything down, runs
cluster B on the classic loop, and diffs the raw SSE payloads.
`cluster_utilization()["serve"]["stream"]` proves the two runs really
took different transports (push frames minted in A, zero in B).
"""

import json
import socket
import time
import urllib.request

import ray_tpu


CFG_KW = dict(vocab_size=384, d_model=64, n_layers=2, n_heads=4,
              max_seq=128)
N_TOKENS = 24


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _decode_once(port):
    """One deterministic streamed completion; returns (token_ids, texts)."""
    body = json.dumps({"model": "m", "prompt": "the quick brown",
                       "max_tokens": N_TOKENS, "stream": True,
                       "temperature": 0.0}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/completions", data=body,
        headers={"Content-Type": "application/json"})
    toks, texts = [], []
    with urllib.request.urlopen(req, timeout=120) as resp:
        for line in resp:
            line = line.decode().strip()
            if not line.startswith("data: "):
                continue
            data = line[6:]
            if data == "[DONE]":
                break
            ev = json.loads(data)
            assert "error" not in ev, ev
            toks.extend(ev.get("token_ids", []) or [])
            for ch in ev.get("choices", []):
                texts.append(ch.get("text", ""))
    return toks, texts


def _run_cluster(monkeypatch, push: str):
    """Fresh cluster with the replica forced off shm; returns the decode
    plus the controller's push-frame count at teardown."""
    from ray_tpu import serve
    from ray_tpu.llm import LLMConfig
    from ray_tpu.llm.openai import build_openai_app
    from ray_tpu.util.state import cluster_utilization

    monkeypatch.setenv("RT_STREAM_FORCE_PUSH", "1")
    monkeypatch.setenv("RT_STREAM_PUSH", push)
    ray_tpu.init(num_cpus=4)
    try:
        port = _free_port()
        app = build_openai_app(LLMConfig(**CFG_KW), max_batch=4,
                               decode_chunk=4)
        serve.run(app, route_prefix="/", port=port)
        toks, texts = _decode_once(port)
        # Counters ride the 1s metrics flusher: give them two flush
        # windows to land at the controller before reading. The legacy
        # leg expects ZERO records, so polling-until-nonzero would just
        # burn the whole window — settle once and read.
        records = 0
        if push == "1":
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                stream = (cluster_utilization().get("serve", {})
                          .get("stream", {}))
                records = int(stream.get("records", 0) or 0)
                if records:
                    break
                time.sleep(0.5)
        else:
            time.sleep(2.2)
            stream = (cluster_utilization().get("serve", {})
                      .get("stream", {}))
            records = int(stream.get("records", 0) or 0)
        serve.shutdown()
        return toks, texts, records
    finally:
        ray_tpu.shutdown()


def test_nak_fallback_byte_identical(shutdown_only, monkeypatch):
    push_toks, push_texts, push_records = _run_cluster(monkeypatch, "1")
    item_toks, item_texts, item_records = _run_cluster(monkeypatch, "0")

    assert len(push_toks) == N_TOKENS
    # Same request, same seeded weights, different transport: identical
    # token ids AND identical per-chunk text payloads.
    assert item_toks == push_toks
    assert "".join(item_texts) == "".join(push_texts)
    # Prove the runs actually differed in transport: the push cluster
    # minted rt_stream_push_records_total, the nakked cluster minted none.
    # (>0, not an exact count: the poll above may catch a mid-stream
    # flush window with only part of the counters landed.)
    assert push_records > 0, (
        "push cluster minted no stream records — did the handshake "
        "really pick the push transport?")
    assert item_records == 0, (
        f"RT_STREAM_PUSH=0 cluster minted {item_records} push records — "
        f"the legacy pin leaked onto the push transport")
